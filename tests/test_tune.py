"""Sim-in-the-loop autotuner (docs/tuning.md).

Covers the four tune/ stages plus the surfaces the tuner reaches into:

1. **Registry walk** — the knob space and the owning config dataclasses
   cannot drift: every scalar field is registered or explicitly
   ``NON_TUNABLE``, every registered default sits on its grid, and the
   docs knob table is the generated one.
2. **Search determinism** — same seed ⇒ bit-identical JSONL journal;
   different seeds diverge; a truncated journal resumes into the
   byte-identical uninterrupted journal; a journal from a different
   run is refused.
3. **Held-out improvement** — the checked-in fingerprint fixture tunes
   to a config that beats the registry defaults on seeds provably
   outside the search's evaluation-seed family.
4. **Sim-vs-live validation** — contrasting candidates rank the same
   in the simulator and on a live tiny engine (Kendall tau + top-1).
5. **Artifact** — round-trips through JSON, boots an engine whose
   resolved knobs hash to the artifact's ``config_hash``, and a warm
   boot from the artifact's manifest compiles nothing.
6. **Catalog swap** — ``maybe_swap_config`` threshold gating, nearest-
   entry selection, and churn protection, inside ``plan_step_slo``.
7. **Env-knob table** — ``DYN_*`` flag spellings validate at config
   construction; typos and malformed values raise, exempt names pass.
8. **Bench pairing** — ``llmctl bench compare`` pairs by
   ``(metric, config_hash)`` and skips differently-tuned runs.
"""

import asyncio
import json
import os

import pytest

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.planner.planner import PlannerConfig
from dynamo_exp_tpu.planner.policy import (
    CatalogEntry,
    PlannerObservation,
    PlannerState,
    SloTargets,
    maybe_swap_config,
    plan_step,
    plan_step_slo,
)
from dynamo_exp_tpu.telemetry.bench_compare import compare_bench
from dynamo_exp_tpu.telemetry.fingerprint import (
    DRIFT_ALERT_THRESHOLD,
    WorkloadFingerprint,
    drift_score,
    load_fingerprint,
)
from dynamo_exp_tpu.tune import (
    SearchSettings,
    TuneResult,
    TuneTarget,
    build_artifact,
    catalog_entry_from_artifact,
    engine_config_from_artifact,
    evaluate,
    kendall_tau,
    load_artifact,
    manifest_from_artifact,
    run_search,
    target_from_fingerprint,
    top_candidates,
    validate_candidates,
    write_artifact,
)
from dynamo_exp_tpu.tune import space
from dynamo_exp_tpu.tune.artifact import resolved_live_knobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "tune_fingerprint.json")


# ------------------------------------------------------------- registry
@pytest.mark.pre_merge
def test_registry_covers_every_scalar_config_field():
    """The registry-walk guard: every bool/int/float field of each
    owning config dataclass is either a registered knob or explicitly
    allowlisted in NON_TUNABLE — and the two sets never overlap or go
    stale. Adding a config field without deciding its tunability fails
    here."""
    from dataclasses import MISSING, fields

    for owner, cls in space.owner_classes().items():
        scalar = {
            f.name
            for f in fields(cls)
            if f.default is not MISSING
            and isinstance(f.default, (bool, int, float))
        }
        registered = {k.name for k in space.KNOBS if k.owner == owner}
        allowed = space.NON_TUNABLE[owner]
        missing = scalar - registered - allowed
        assert not missing, (
            f"{owner}: undecided fields {sorted(missing)} — register a "
            f"Knob or allowlist in NON_TUNABLE with a reason"
        )
        assert not registered & allowed, (
            f"{owner}: both registered and allowlisted: "
            f"{sorted(registered & allowed)}"
        )
        assert not allowed - scalar, (
            f"{owner}: stale NON_TUNABLE entries: {sorted(allowed - scalar)}"
        )
        assert not registered - scalar, (
            f"{owner}: registered knobs with no matching scalar field: "
            f"{sorted(registered - scalar)}"
        )


@pytest.mark.pre_merge
def test_registry_defaults_sit_on_their_grids():
    for knob in space.KNOBS:
        assert space.default_value(knob) in knob.grid, (
            f"{knob.name}: dataclass default {space.default_value(knob)!r} "
            f"not on grid {knob.grid}"
        )


@pytest.mark.pre_merge
def test_knob_table_doc_sync():
    """docs/tuning.md carries the generated knob table verbatim — the
    same discipline as the telemetry metric and dynlint waiver doc
    guards."""
    with open(os.path.join(REPO, "docs", "tuning.md")) as f:
        doc = f.read()
    assert space.render_knob_table() in doc, (
        "docs/tuning.md knob table is stale; paste the output of "
        "space.render_knob_table()"
    )


@pytest.mark.pre_merge
def test_config_hash_canonical_and_discriminating():
    knobs = space.defaults("engine")
    h = space.config_hash(knobs)
    assert h == space.config_hash(dict(reversed(list(knobs.items()))))
    changed = dict(knobs, max_decode_slots=knobs["max_decode_slots"] * 2)
    assert space.config_hash(changed) != h
    assert len(h) == 12 and len(space.space_digest()) == 16


@pytest.mark.pre_merge
def test_override_mapping_helpers():
    with pytest.raises(KeyError):
        space.split_overrides({"not_a_knob": 1})
    over = {"max_decode_slots": 16, "max_inflight": 32, "decode_window": 8}
    sim_kw = space.sim_kwargs_from_overrides(over)
    # Engine knobs map through their SimConfig mirror; sim-only knobs
    # pass through; live-only knobs (decode_window) are dropped.
    assert sim_kw == {"slots_per_instance": 16, "max_inflight": 32}
    eng_kw = space.engine_kwargs_from_overrides(over)
    assert eng_kw == {"max_decode_slots": 16, "decode_window": 8}


# --------------------------------------------------------------- search
def _target(n=16) -> TuneTarget:
    return TuneTarget(kind="synthetic", name="burst", requests=n)


def _settings(**over) -> SearchSettings:
    base = dict(
        seed=3, budget=10, eval_seeds=2, base_sim={"initial_instances": 1}
    )
    return SearchSettings(**(base | over))


def test_search_same_seed_bit_identical_journal(tmp_path):
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    ra = run_search(_target(), _settings(), journal_path=pa)
    rb = run_search(_target(), _settings(), journal_path=pb)
    with open(pa, "rb") as fa, open(pb, "rb") as fb:
        assert fa.read() == fb.read()
    assert ra.best_overrides == rb.best_overrides
    assert ra.best_score == rb.best_score


def test_search_different_seeds_diverge(tmp_path):
    ra = run_search(_target(), _settings(seed=3))
    rb = run_search(_target(), _settings(seed=4))
    # Headers differ trivially (the seed is in them); the *trial*
    # sequences must too — different seed means different evaluation
    # seeds and a different coordinate order.
    assert ra.journal[1:] != rb.journal[1:]


def test_truncated_journal_resumes_byte_identical(tmp_path):
    path = str(tmp_path / "j.jsonl")
    run_search(_target(), _settings(), journal_path=path)
    with open(path, "rb") as f:
        full = f.read()
    lines = full.decode().splitlines()
    torn = "\n".join(lines[:5]) + '\n{"kind": "tri'  # half-written tail
    with open(path, "w") as f:
        f.write(torn)
    run_search(_target(), _settings(), journal_path=path, resume=True)
    with open(path, "rb") as f:
        assert f.read() == full


def test_resume_refuses_foreign_journal(tmp_path):
    path = str(tmp_path / "j.jsonl")
    run_search(_target(), _settings(seed=3), journal_path=path)
    with pytest.raises(ValueError, match="different run"):
        run_search(
            _target(), _settings(seed=4), journal_path=path, resume=True
        )


@pytest.mark.pre_merge
def test_top_candidates_distinct_rung1_best_first():
    def trial(overrides, rung, score):
        return {
            "kind": "trial", "overrides": overrides, "rung": rung,
            "score": score,
        }

    result = TuneResult(
        best_overrides={}, best_score=3.0, default_score=1.0, trials=5,
        journal=[
            {"kind": "header"},
            trial({}, 1, 1.0),
            trial({"a": 1}, 0, 9.0),  # rung 0 never surfaces
            trial({"a": 1}, 1, 3.0),
            trial({"a": 1}, 1, 2.5),  # duplicate config, kept once
            trial({"b": 2}, 1, 2.0),
        ],
        target_digest="t", seed=0,
    )
    assert top_candidates(result, 2) == [{"a": 1}, {"b": 2}]
    assert top_candidates(result, 9) == [{"a": 1}, {"b": 2}, {}]


def test_tuned_beats_defaults_on_held_out_seeds():
    """The tune-smoke contract (make tune-smoke runs the CLI spelling):
    searching the checked-in fingerprint fixture finds overrides that
    beat the registry defaults on seeds outside the search's
    ``seed*1000+i`` evaluation family."""
    target = target_from_fingerprint(load_fingerprint(FIXTURE))
    settings = SearchSettings(
        seed=0, budget=96, eval_seeds=2, base_sim={"initial_instances": 1}
    )
    result = run_search(target, settings)
    assert result.best_overrides, "search found nothing over the defaults"
    assert result.improvement > 0
    held_out = [777000, 777001, 777002]
    tuned = sum(
        evaluate(result.best_overrides, target, settings, s)["score"]
        for s in held_out
    )
    default = sum(
        evaluate({}, target, settings, s)["score"] for s in held_out
    )
    assert tuned > default, (
        f"tuned {result.best_overrides} lost to defaults on held-out "
        f"seeds: {tuned:.3f} <= {default:.3f}"
    )


# ----------------------------------------------------- sim-vs-live rank
@pytest.mark.pre_merge
def test_kendall_tau_units():
    assert kendall_tau([1.0], [2.0]) == 1.0
    assert kendall_tau([1.0, 2.0, 3.0], [10.0, 20.0, 30.0]) == 1.0
    assert kendall_tau([1.0, 2.0, 3.0], [30.0, 20.0, 10.0]) == -1.0
    assert kendall_tau([1.0, 1.0], [1.0, 2.0]) == 0.0  # tie contributes 0


def test_sim_and_live_rank_agree_on_contrasting_candidates():
    """The validation stage's own contract: a candidate that strangles
    edge admission (``max_inflight=1`` sheds most of the burst) must
    lose to the default envelope in BOTH the simulator and the live
    tiny harness — same clamped workload on both sides, shedding for
    the same causal reason, so only the configs differ. The target is
    a long-prompt fingerprint (mass in the 64-128 ISL bucket) so the
    burst genuinely overlaps inside the harness. The live SLO gates are
    lifted out of the way: ranking here must come from goodput (24 vs
    ~192 tokens over comparable wall time), not from whether this
    host's cold-start compile stall happens to cross a fixed ITL gate."""
    fp = WorkloadFingerprint(
        n=48,
        isl_hist=(0, 0, 0, 48, 0, 0, 0, 0, 0, 0, 0),
        osl_hist=(0, 0, 0, 48, 0, 0, 0, 0, 0, 0, 0),
        priority_mix=(0.0, 1.0, 0.0),
        arrival_rate_rps=8.0,
    )
    target = target_from_fingerprint(fp)
    candidates = [{}, {"max_inflight": 1}]
    verdict = asyncio.run(
        validate_candidates(
            candidates, target, seed=5, n=8,
            slo_ttft_s=1e9, slo_itl_s=1e9,
        )
    )
    assert verdict["top1_agreement"] is True, verdict["candidates"]
    assert verdict["kendall_tau"] == 1.0, verdict["candidates"]
    assert verdict["agreed"] is True
    assert verdict["sim_scores"][0] > verdict["sim_scores"][1]
    assert verdict["live_scores"][0] > verdict["live_scores"][1]


# ------------------------------------------------------------- artifact
def _result(**over) -> TuneResult:
    base = dict(
        best_overrides={
            "max_decode_slots": 2,
            "num_pages": 64,
            "page_size": 8,
            "prefill_chunk": 16,
            "decode_window": 4,
        },
        best_score=2.0, default_score=1.0, trials=7,
        journal=[], target_digest="fixture", seed=0,
    )
    return TuneResult(**(base | over))


async def _collect(engine, prompt, max_tokens=8):
    from dynamo_exp_tpu.protocols.common import BackendInput

    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = True
    stream = await engine.generate(b.to_dict())
    toks = []
    async for item in stream:
        toks.extend(item.get("token_ids", []))
    return toks


def test_artifact_round_trip_boots_with_zero_compiles(tmp_path):
    """The emission contract: the artifact's resolved knobs hash to its
    ``config_hash`` (the bench stamp), and a boot from the artifact's
    manifest against a populated persistent cache compiles nothing."""
    from dynamo_exp_tpu.aot import manifest_for_engine

    fp = load_fingerprint(FIXTURE)
    shape = {"max_model_len": 128, "kv_dtype": "float32"}
    art0 = build_artifact(
        _result(), preset="tiny", shape=shape, fingerprint=fp
    )
    probe = TPUEngine(
        engine_config_from_artifact(art0, model=TINY),
        mesh=single_device_mesh(), seed=0,
    )
    art = build_artifact(
        _result(), preset="tiny", shape=shape,
        manifest=manifest_for_engine(probe), fingerprint=fp,
    )
    path = str(tmp_path / "tuned.json")
    write_artifact(art, path)
    art = load_artifact(path)

    cache = str(tmp_path / "cache")

    def boot():
        cfg = engine_config_from_artifact(art, model=TINY)
        # The booted engine's resolved knobs ARE the artifact's hash —
        # a bench run of this engine pairs against the tuned baseline.
        assert (
            space.config_hash(space.resolved_engine_knobs(cfg))
            == art["config_hash"]
        )
        eng = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
        eng.prewarm(manifest_from_artifact(art), cache_dir=cache)
        toks = asyncio.run(_collect(eng, range(20, 36)))
        m = eng.metrics()
        eng.stop()
        return m, toks

    m1, toks1 = boot()
    m2, toks2 = boot()
    assert m2["dispatch"]["ragged"]["compile_misses"] == 0
    assert m2["dispatch"]["ragged"]["compile_total_s"] == 0.0
    assert toks1 == toks2

    entry = catalog_entry_from_artifact(art, name="tuned-burst")
    assert entry.name == "tuned-burst"
    assert entry.config_hash == art["config_hash"]
    assert dict(entry.overrides) == art["overrides"]
    assert entry.fingerprint.digest() == fp.digest()


@pytest.mark.pre_merge
def test_artifact_guards():
    art = build_artifact(_result(), preset="tiny")
    assert art["fingerprint"] is None
    with pytest.raises(ValueError, match="no target fingerprint"):
        catalog_entry_from_artifact(art)
    # Version check on load.
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "a.json")
        write_artifact(dict(art, version=99), path)
        with pytest.raises(ValueError, match="version"):
            load_artifact(path)


# --------------------------------------------------------- catalog swap
FP_A = WorkloadFingerprint(n=10, isl_hist=(10, 0), osl_hist=(10, 0))
FP_B = WorkloadFingerprint(n=10, isl_hist=(0, 10), osl_hist=(0, 10))


def _entry(name, fp, **over):
    base = dict(
        fingerprint=fp,
        overrides=(("max_decode_slots", 32),),
        config_hash="abc123def456",
    )
    return CatalogEntry(name=name, **(base | over))


def _swap_obs(**over):
    base = dict(
        num_prefill=0, num_decode=2,
        drift_score=drift_score(FP_B, FP_A), fingerprint=FP_B,
    )
    return PlannerObservation(**(base | over))


def _pcfg(**over):
    return PlannerConfig(**(dict(max_tpu_budget=8, min_endpoint=1) | over))


@pytest.mark.pre_merge
def test_swap_gating():
    cfg = _pcfg(config_catalog=(_entry("b", FP_B),))
    # The drifted fixture pair really is past the shared threshold.
    assert drift_score(FP_B, FP_A) >= DRIFT_ALERT_THRESHOLD
    # Below threshold: no-op, silently.
    swap, active, notes = maybe_swap_config(
        _swap_obs(drift_score=DRIFT_ALERT_THRESHOLD - 0.01),
        PlannerState(), cfg,
    )
    assert swap is None and active == "" and notes == []
    # No fingerprint plane wired: no-op.
    swap, _, _ = maybe_swap_config(
        _swap_obs(fingerprint=None), PlannerState(), cfg
    )
    assert swap is None
    # Empty catalog: no-op.
    swap, _, _ = maybe_swap_config(_swap_obs(), PlannerState(), _pcfg())
    assert swap is None


@pytest.mark.pre_merge
def test_swap_picks_nearest_entry():
    cfg = _pcfg(config_catalog=(_entry("a", FP_A), _entry("b", FP_B)))
    swap, active, notes = maybe_swap_config(_swap_obs(), PlannerState(), cfg)
    assert swap is not None and active == "b"
    assert swap["name"] == "b"
    assert swap["config_hash"] == "abc123def456"
    assert swap["drift_after"] < swap["drift_before"]
    assert swap["overrides"] == {"max_decode_slots": 32}


@pytest.mark.pre_merge
def test_swap_churn_protection():
    # Already on the nearest entry: no swap, explained.
    cfg = _pcfg(config_catalog=(_entry("b", FP_B),))
    swap, active, notes = maybe_swap_config(
        _swap_obs(), PlannerState(active_config="b"), cfg
    )
    assert swap is None and active == "b"
    assert any("already active" in n for n in notes)
    # Best entry no nearer than current drift: swapping would churn.
    cfg = _pcfg(config_catalog=(_entry("a", FP_A),))
    swap, active, notes = maybe_swap_config(_swap_obs(), PlannerState(), cfg)
    assert swap is None and active == ""
    assert any("no catalog entry nearer" in n for n in notes)


@pytest.mark.pre_merge
def test_plan_step_slo_folds_catalog_swap():
    cfg = _pcfg(config_catalog=(_entry("b", FP_B),))
    obs = _swap_obs(kv_load=(0.5, 0.5))
    decision, state = plan_step_slo(obs, PlannerState(), cfg, SloTargets())
    assert decision.config_swap is not None
    assert decision.config_swap["name"] == "b"
    assert state.active_config == "b"
    # Next interval, same drift: the entry is active, no re-swap.
    decision, state = plan_step_slo(obs, state, cfg, SloTargets())
    assert decision.config_swap is None
    assert state.active_config == "b"


@pytest.mark.pre_merge
def test_reactive_plan_step_carries_active_config():
    decision, state = plan_step(
        PlannerObservation(num_prefill=0, num_decode=2, kv_load=(0.5,)),
        PlannerState(active_config="x"), _pcfg(),
    )
    assert state.active_config == "x"


# ------------------------------------------------------- env-knob table
def _ecfg(**over):
    return EngineConfig(model=TINY, eos_token_ids=[], **over)


@pytest.mark.pre_merge
def test_env_flag_spellings(monkeypatch):
    monkeypatch.setenv("DYN_KV_PACKING", "yes")
    assert _ecfg().kv_packing is True
    monkeypatch.setenv("DYN_KV_PACKING", "off")
    assert _ecfg(kv_packing=True).kv_packing is False
    monkeypatch.setenv("DYN_KV_PACKING", "")
    assert _ecfg(kv_packing=True).kv_packing is True  # unset = untouched
    monkeypatch.setenv("DYN_KV_PACKING", "maybe")
    with pytest.raises(ValueError, match="not a recognized flag spelling"):
        _ecfg()


@pytest.mark.pre_merge
def test_env_typo_rejected_exempt_name_passes(monkeypatch):
    monkeypatch.setenv("DYN_KV_PACKNG", "1")  # the silent-no-op bug class
    with pytest.raises(ValueError, match="unknown engine env knob"):
        _ecfg()
    monkeypatch.delenv("DYN_KV_PACKNG")
    # telemetry.fleet's bandwidth prior lives under the family but is
    # exempt — it must not trip the engine's table.
    monkeypatch.setenv("DYN_KV_DEFAULT_BW_BPS", "1e9")
    _ecfg()  # must not raise


@pytest.mark.pre_merge
def test_env_spec_semantics(monkeypatch):
    monkeypatch.setenv("DYN_SPEC", "1")
    assert _ecfg().spec_mode == "ngram"
    monkeypatch.setenv("DYN_SPEC", "0")
    assert _ecfg().spec_mode == "off"
    monkeypatch.setenv("DYN_SPEC", "ngram")
    assert _ecfg().spec_mode == "ngram"
    # An explicit spec_mode always wins over the env toggle.
    monkeypatch.setenv("DYN_SPEC", "0")
    assert _ecfg(spec_mode="ngram").spec_mode == "ngram"
    monkeypatch.setenv("DYN_SPEC", "bogus_drafter")
    with pytest.raises(ValueError, match="neither a flag spelling"):
        _ecfg()


@pytest.mark.pre_merge
def test_env_proactive_grace(monkeypatch):
    monkeypatch.setenv("DYN_KV_PROACTIVE", "1")
    assert _ecfg(proactive_offload_grace_s=-1.0).proactive_offload_grace_s \
        == 0.0
    assert _ecfg(proactive_offload_grace_s=0.2).proactive_offload_grace_s \
        == 0.2
    monkeypatch.setenv("DYN_KV_PROACTIVE", "0")
    assert _ecfg(proactive_offload_grace_s=0.2).proactive_offload_grace_s \
        == -1.0


# ------------------------------------------------- bench config pairing
def _line(metric="decode tok/s", value=100.0, platform="cpu", **extra):
    return {
        "metric": metric, "unit": "tok/s", "value": value,
        "platform": platform, **extra,
    }


@pytest.mark.pre_merge
def test_bench_compare_same_hash_still_flags_regressions():
    report = compare_bench(
        [_line(value=100.0, config_hash="aaa")],
        [_line(value=50.0, config_hash="aaa")],
    )
    assert [f.kind for f in report.findings] == ["regression"]


@pytest.mark.pre_merge
def test_bench_compare_skips_differently_tuned_runs():
    report = compare_bench(
        [_line(value=100.0, config_hash="aaa")],
        [_line(value=50.0, config_hash="bbb")],
    )
    assert report.compared == 0 and not report.findings
    assert any("differently-tuned" in s for s in report.skipped)


@pytest.mark.pre_merge
def test_bench_compare_pairs_by_config_hash_among_same_metric():
    """An old capture holding the same metric under two configs pairs
    the new line with ITS config, not whichever parsed last."""
    old = [
        _line(value=100.0, config_hash="aaa"),
        _line(value=50.0, config_hash="bbb"),
    ]
    report = compare_bench(old, [_line(value=100.0, config_hash="aaa")])
    assert report.compared == 1 and report.findings == []


@pytest.mark.pre_merge
def test_bench_compare_legacy_untagged_lines_pair_by_metric():
    # Checked-in BENCH_r*.json captures predate the stamp: one side (or
    # both) untagged keeps the metric-name pairing unchanged.
    report = compare_bench(
        [_line(value=100.0)], [_line(value=50.0, config_hash="bbb")]
    )
    assert report.compared == 1
    assert [f.kind for f in report.findings] == ["regression"]


# ------------------------------------------------------------- evaluate
@pytest.mark.pre_merge
def test_evaluate_pinned_workload_overrides_seed_generation():
    target = _target(n=8)
    workload = target.workload(123)
    a = evaluate({}, target, _settings(), seed=123)
    b = evaluate({}, target, _settings(), seed=999, workload=workload)
    # Same requests, same sim seed difference only: the pinned list is
    # what ran (scores computed from it, not from seed-999 generation).
    c = evaluate({}, target, _settings(), seed=123, workload=workload)
    assert c == a
    assert isinstance(b["score"], float)


def test_journal_lines_are_canonical_json():
    result = run_search(_target(), _settings(budget=4))
    for line in result.journal:
        blob = json.dumps(line, sort_keys=True)
        assert json.loads(blob) == line
