"""Engine registry: test engines and the TPU engine behind one seam.

Capability parity with ``/root/reference/lib/llm/src/engines.rs``: "core"
engines speak token-in/token-out (``BackendInput`` -> ``LLMEngineOutput``)
and get wrapped by the preprocessor + backend; "full" engines accept
OpenAI requests directly. ``MultiNodeConfig`` carries multi-host bring-up
parameters (JAX distributed coordinator instead of Ray/torch.distributed).
"""

from __future__ import annotations

from dataclasses import dataclass

from .echo import EchoEngineCore, EchoEngineFull


@dataclass
class MultiNodeConfig:
    """Multi-host engine bring-up (maps to jax.distributed.initialize)."""

    num_nodes: int = 1
    node_rank: int = 0
    coordinator_address: str = ""


def make_engine(name: str, **kwargs):
    """Engine factory by name. ``jax`` is the native TPU engine; the echo
    engines validate the serving pipeline without hardware."""
    if name == "echo_core":
        return EchoEngineCore(**kwargs)
    if name == "echo_full":
        return EchoEngineFull(**kwargs)
    if name == "jax":
        from ..engine import TpuEngine

        return TpuEngine.build(**kwargs)
    raise ValueError(f"unknown engine {name!r}")


__all__ = ["EchoEngineCore", "EchoEngineFull", "MultiNodeConfig", "make_engine"]
