"""Bench regression comparator (``llmctl bench compare A.json B.json``).

Compares two bench captures — raw ``bench.py`` JSONL output or the
checked-in ``BENCH_r*.json`` wrappers (``{"n", "cmd", "rc", "tail",
"parsed"}``) — metric by metric, and flags regressions: throughput
(``tok/s`` lines) dropping more than the threshold, or any latency
field (``*ttft*``, ``*itl*`` — p50/p99 alike) growing more than the
threshold.

Platform-tag aware: ``bench.py`` tags every line with the platform it
actually ran on (the TPU tunnel has been down since r02, so r02+ are
CPU-fallback lines), and a CPU number is not comparable to a chip
number — such pairs are reported as skipped, never as regressions.
Captures with no comparable pairs (e.g. two failed runs) compare clean:
the pre-merge CI step runs this over the checked-in trajectory, and a
dead tunnel must not block merges.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


def load_bench_lines(path: str) -> list[dict]:
    """Every bench metric line in ``path``. Accepts raw bench JSONL
    (one metric object per line) and the BENCH_r* wrapper shape (metric
    lines recovered from ``parsed`` + the stdout ``tail``). Unparseable
    lines are skipped — a crashed run yields [] rather than an error."""
    with open(path) as f:
        text = f.read()
    lines: list[dict] = []
    seen: set[str] = set()

    def add(obj) -> None:
        if isinstance(obj, dict) and obj.get("metric"):
            key = json.dumps(obj, sort_keys=True)
            if key not in seen:
                seen.add(key)
                lines.append(obj)

    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and ("tail" in doc or "parsed" in doc):
        add(doc.get("parsed"))
        for raw in str(doc.get("tail", "")).splitlines():
            raw = raw.strip()
            if raw.startswith("{"):
                try:
                    add(json.loads(raw))
                except ValueError:
                    continue
        return lines
    if isinstance(doc, dict):
        add(doc)
        return lines
    if isinstance(doc, list):
        for obj in doc:
            add(obj)
        return lines
    for raw in text.splitlines():
        raw = raw.strip()
        if raw.startswith("{"):
            try:
                add(json.loads(raw))
            except ValueError:
                continue
    return lines


_LATENCY_MARKERS = ("ttft", "itl", "latency")


def _latency_fields(line: dict) -> dict[str, float]:
    out = {}
    for key, val in line.items():
        if not isinstance(val, (int, float)):
            continue
        if any(m in key for m in _LATENCY_MARKERS) and key.endswith("_s"):
            out[key] = float(val)
    return out


@dataclass
class Finding:
    metric: str
    field: str
    old: float
    new: float
    change: float  # signed fraction (+ = grew)
    kind: str  # "regression" | "improvement" | "skipped"
    note: str = ""


@dataclass
class CompareReport:
    findings: list[Finding] = field(default_factory=list)
    compared: int = 0
    skipped: list[str] = field(default_factory=list)

    @property
    def regressions(self) -> list[Finding]:
        return [f for f in self.findings if f.kind == "regression"]

    @property
    def ok(self) -> bool:
        return not self.regressions


def compare_bench(
    old_lines: list[dict],
    new_lines: list[dict],
    threshold: float = 0.10,
) -> CompareReport:
    """Flag per-metric regressions beyond ``threshold`` (default 10%).

    Only metrics present in BOTH captures compare; a platform-tag
    mismatch (chip vs CPU-fallback line) skips the pair with a note.
    Lines carrying a ``config_hash`` (tune/space.py knob stamp) pair by
    (metric, config_hash) — a run knobbed differently is a different
    experiment, skipped rather than flagged as a regression; untagged
    legacy lines keep pairing by metric alone. Throughput compares on
    ``value`` for ``tok/s`` lines (lower = worse); every
    ``*ttft*``/``*itl*`` latency field compares too (higher = worse)."""
    report = CompareReport()
    by_metric = {ln["metric"]: ln for ln in old_lines}
    by_config = {
        (ln["metric"], ln["config_hash"]): ln
        for ln in old_lines
        if ln.get("config_hash")
    }
    for new in new_lines:
        old = by_config.get((new["metric"], new.get("config_hash")))
        if old is None:
            old = by_metric.get(new["metric"])
        if old is None:
            continue
        h_old = old.get("config_hash")
        h_new = new.get("config_hash")
        if h_old and h_new and h_old != h_new:
            report.skipped.append(
                f"{new['metric']}: knob config {h_old} vs {h_new} — "
                f"differently-tuned runs, not comparable"
            )
            continue
        p_old = old.get("platform")
        p_new = new.get("platform")
        if p_old != p_new:
            report.skipped.append(
                f"{new['metric']}: platform {p_old or 'untagged'} vs "
                f"{p_new or 'untagged'} — not comparable"
            )
            continue
        report.compared += 1

        def judge(fld: str, a: float, b: float, higher_is_worse: bool,
                  metric: str = new["metric"]) -> None:
            if a <= 0:
                return
            change = (b - a) / a
            worse = change > threshold if higher_is_worse else (
                change < -threshold
            )
            better = change < -threshold if higher_is_worse else (
                change > threshold
            )
            kind = (
                "regression" if worse else "improvement" if better else None
            )
            if kind:
                report.findings.append(
                    Finding(metric, fld, a, b, round(change, 4), kind)
                )

        unit = str(old.get("unit") or "")
        if unit.endswith("tok/s") and isinstance(
            new.get("value"), (int, float)
        ) and isinstance(old.get("value"), (int, float)):
            judge(f"value({unit})", float(old["value"]), float(new["value"]),
                  higher_is_worse=False)
        # Spot-reclamation sweep fields (bench.py --reclaim-sweep):
        # billed chip-seconds are the spot-economics denominator
        # (growing spend at equal goodput is a regression), the
        # migrated fraction is the live-migration hit rate (falling
        # means more journal re-prefill), and goodput per billed
        # chip-second is the headline ratio the sweep exists for.
        for fld, worse_high in (
            ("billed_chip_seconds", True),
            ("migrated_fraction", False),
            ("goodput_per_billed_chip_s", False),
        ):
            a_v, b_v = old.get(fld), new.get(fld)
            if isinstance(a_v, (int, float)) and isinstance(
                b_v, (int, float)
            ):
                judge(fld, float(a_v), float(b_v),
                      higher_is_worse=worse_high)
        lat_old, lat_new = _latency_fields(old), _latency_fields(new)
        for fld in sorted(set(lat_old) & set(lat_new)):
            judge(fld, lat_old[fld], lat_new[fld], higher_is_worse=True)
        # Per-request anatomy components (bench.py _anatomy_stats, mean
        # seconds per finished request): attribute a latency regression
        # to the component that moved. Seconds spent — higher is worse.
        an_old = old.get("anatomy") or {}
        an_new = new.get("anatomy") or {}
        for fld in sorted(set(an_old) & set(an_new)):
            a_v, b_v = an_old[fld], an_new[fld]
            if isinstance(a_v, (int, float)) and isinstance(
                b_v, (int, float)
            ):
                judge(f"anatomy.{fld}", float(a_v), float(b_v),
                      higher_is_worse=True)
    return report


def render_compare(report: CompareReport, a: str, b: str) -> str:
    lines = [
        f"bench compare: {a} -> {b}  "
        f"({report.compared} comparable metric(s), "
        f"{len(report.skipped)} skipped)"
    ]
    for f in report.findings:
        arrow = "REGRESSION" if f.kind == "regression" else "improvement"
        lines.append(
            f"  {arrow}: {f.metric} {f.field} "
            f"{f.old:g} -> {f.new:g} ({f.change:+.1%})"
        )
    for note in report.skipped:
        lines.append(f"  skipped: {note}")
    if report.compared == 0:
        lines.append(
            "  no comparable metrics (failed runs or disjoint modes) — "
            "nothing to flag"
        )
    elif report.ok:
        lines.append("  no regressions beyond threshold")
    return "\n".join(lines)
