"""Profiling hooks: jax.profiler server + on-demand trace capture.

Reference capability: SURVEY.md §5 tracing — the reference has JSONL
tracing but no accelerator profiler; the TPU-native extension is
``jax.profiler`` (XLA/TPU timeline in TensorBoard / Perfetto):

- ``start_profiler_server(port)`` — expose the live profiling gRPC
  endpoint so ``tensorboard --logdir`` or ``xprof`` can attach to a
  serving worker (``run.py --profiler-port``).
- ``capture_trace(dir, duration_ms)`` — one-shot programmatic capture
  around the engine's hot loop.
"""

from __future__ import annotations

import contextlib
import logging
import threading

logger = logging.getLogger(__name__)

_server_started = False
_lock = threading.Lock()


def start_profiler_server(port: int) -> bool:
    """Idempotently start the jax.profiler collection server. Returns
    False (with a log line) when the backend doesn't support it."""
    global _server_started
    with _lock:
        if _server_started:
            return True
        try:
            import jax

            jax.profiler.start_server(port)
            _server_started = True
            logger.info("jax profiler server on port %d", port)
            return True
        except Exception as e:  # noqa: BLE001 - profiling is best-effort
            logger.warning("profiler server failed to start: %s", e)
            return False


@contextlib.contextmanager
def trace_to(log_dir: str):
    """Context manager tracing the enclosed block into ``log_dir``
    (viewable in TensorBoard's profile plugin)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def capture_trace(log_dir: str, duration_ms: int = 2000) -> None:
    """Capture ``duration_ms`` of device activity into ``log_dir``."""
    import time

    with trace_to(log_dir):
        time.sleep(duration_ms / 1000.0)
