"""Continuous-batching scheduler: sequences, decode slots, admission.

This is the TPU replacement for the scheduling vLLM provided the
reference for free (SURVEY.md §2.9). Single-writer: all mutation happens
on the engine loop thread.

Policy (v2): **batched, chunked, decode-interleaved prefill.** Waiting
prompts are admitted to slots as soon as pages are available and then
prefilled in bucketed chunks, several sequences per dispatch — so a
burst of arrivals shares prefill forwards instead of serializing, and a
long prompt is fed ``prefill_chunk`` tokens at a time so decode steps
interleave between chunks instead of stalling behind one giant forward.
Decode runs every loop iteration over all ACTIVE slots; sequences whose
prompt is still being chunked sit in PREFILL state and don't join decode
until their first token is sampled.
"""

from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

if TYPE_CHECKING:
    import numpy as np

from itertools import islice

from ..protocols.common import BackendInput, FinishReason
from ..telemetry import get_telemetry
from ..tokens import chain_hash, compute_block_hash
from .config import EngineConfig
from .kv_manager import KvPageManager
from .tiering import KvFootprintForecast, select_packed_index


@dataclass
class RemoteKv:
    """Prefill computed elsewhere (disaggregation): the first sampled
    token plus the prompt's KV pages, host-bounced as numpy arrays of
    shape [L, page_size, Hkv, D] each (reference capability:
    ``RemotePrefillParams`` + NIXL block writes,
    ``/root/reference/container/deps/vllm/…patch:4175+``)."""

    first_token: int
    pages: "list[tuple[np.ndarray, np.ndarray]]"
    # Suffix-only transfer (docs/prefix_sharing.md): ``pages[i]`` is
    # prompt page ``skip_pages + i`` — the decode side already holds
    # the first ``skip_pages`` pages (pinned under ``pin_lease`` since
    # the routing decision; the engine releases the pin at inject).
    skip_pages: int = 0
    pin_lease: str | None = None


class SeqState(enum.Enum):
    WAITING = "waiting"  # queued, no slot/pages yet
    PREFILL = "prefill"  # slot + pages bound, prompt being chunked in
    ACTIVE = "active"  # decoding (device slot live)
    FINISHED = "finished"


@dataclass
class Sequence:
    """One in-flight request's full engine-side state."""

    request_id: str
    prompt: list[int]
    stop: "BackendInput"
    # emit(tokens, finish_reason, logprobs_pack=None) — the third arg is
    # the optional (per-token logprobs, top alternatives) payload.
    emit: Callable[..., None]
    is_cancelled: Callable[[], bool]
    state: SeqState = SeqState.WAITING
    slot: int = -1
    page_ids: list[int] = field(default_factory=list)
    cached_len: int = 0  # prefix reused from the page pool
    tokens: list[int] = field(default_factory=list)  # prompt + confirmed
    generated: int = 0
    # Chunked prefill progress: prompt tokens already *dispatched* to the
    # device (including the reused prefix). The prompt is fully in flight
    # once prefill_sent == len(prompt).
    prefill_sent: int = 0
    # Chained hash state for registering full pages (router events + reuse).
    parent_hash: int | None = None
    hashed_pages: int = 0  # count of pages already registered
    # Set when the pool ran dry mid-decode; slot idles until a page frees.
    stalled: bool = False
    # When a *hard* stall began (the row cannot even feed its next
    # token): the KV-pressure preemption grace clock. 0.0 = not stalled.
    stalled_since: float = 0.0
    # Admission priority class (0=low, 1=normal, 2=high): the edge sheds
    # low first; KV-pressure preemption victimizes low first.
    priority: int = 1
    # End-to-end deadline (unix seconds, 0 = none), captured from the
    # request context at submission so the engine can reap expired work
    # from the waiting queue before it wastes prefill.
    deadline_unix: float = 0.0
    # KV-pressure preemptions suffered so far (bounded per request by
    # EngineConfig.max_preemptions_per_seq).
    preemptions: int = 0
    # Stop discovered while a chained decode window was still in flight:
    # the finish (and its page release) is deferred until that window is
    # consumed, so the device can't write into reallocated pages. The
    # on-device stop already flipped the row's position to -1, making
    # the in-flight window's output for it pure discard.
    pending_finish: "FinishReason | None" = None
    # G2→G1 injections the engine must dispatch before this prefill:
    # (page_id, seq_hash, k_page, v_page) per page (see kv_manager).
    pending_uploads: list = field(default_factory=list)
    # Prefix sharing (docs/prefix_sharing.md): attached pages another
    # sequence is still filling — this sequence's first prefill dispatch
    # waits until every one is filled (or claims orphans left by a dead
    # filler and re-fills them itself).
    wait_fill: list = field(default_factory=list)
    # Shared partial-tail page (radix partial_match attach): must be
    # made private (copy-on-write) before this sequence's first decode
    # write lands in it. -1 = none / already resolved.
    shared_tail_pid: int = -1
    # Prompt pages already marked filled with the page manager (the
    # engine marks [fill_marked, prefill_sent//ps) after each chunk
    # dispatch; claims of orphaned pages rewind it).
    fill_marked: int = 0
    # Chained hashes of all full prompt pages (from Allocation) so
    # register_full_pages never rehashes prompt tokens.
    prompt_hashes: list[int] = field(default_factory=list)
    # Disaggregation: KV pages precomputed by a remote prefill worker —
    # the engine injects them and skips the prefill compute entirely.
    remote_kv: "RemoteKv | None" = None
    # Prefill-extraction mode (this engine IS the remote prefill worker):
    # after prefill, gather the prompt's KV pages and hand them here as
    # (first_token, [(k_page, v_page), ...]).
    extract_cb: "Callable[[int, list], None] | None" = None
    # Suffix-only extraction: leading prompt pages the decode side
    # already holds (pinned there) — not gathered, not shipped.
    extract_skip: int = 0
    # Telemetry: the request's trace context (captured from the
    # submitting task's contextvar — the engine loop thread doesn't
    # share it) plus unix-time stage stamps the engine fills in.
    trace: "object | None" = None
    submitted_at: float = 0.0
    admitted_at: float = 0.0
    first_token_at: float = 0.0
    last_emit_at: float = 0.0
    # Set when the prompt KV was injected from a remote prefill worker.
    remote_prefilled: bool = False
    # Effective sampling seed (request's sampling_options.seed, or one
    # the engine drew at submission). Sampling is counter-based per row —
    # every draw is keyed by (sample_seed, absolute token position) — so
    # a request replayed with the same seed reproduces its tokens on any
    # instance, any batch shape (the failover-replay guarantee).
    sample_seed: int = 0
    # Speculative decoding (docs/speculative.md): this request's verify
    # dispatches, draft tokens proposed/accepted, and tokens emitted
    # through speculation — the decode span reports the per-request
    # tokens-per-dispatch the simulator's service-time fit consumes.
    spec_dispatches: int = 0
    spec_draft_tokens: int = 0
    spec_accepted_tokens: int = 0
    spec_emitted_tokens: int = 0
    # Predictive KV tiering (docs/engine_perf.md "Predictive KV
    # tiering"). Cached prompt block hashes for the admission footprint
    # forecast and the prefetch planner (invalidated by preemption
    # surgery — the prompt changes).
    forecast_hashes: "list[int] | None" = None
    # Packed admission: times this waiting sequence has been bypassed
    # by a smaller forecast (bounded by packing_max_defers).
    packing_defers: int = 0
    # Proactive offload: the row's swap record while its cold pages
    # live in the host tier (None = fully resident), when the swap
    # began, and how many times this row has been swapped out.
    swap: "object | None" = None
    swapped_since: float = 0.0
    swaps: int = 0
    # Request anatomy accumulators (telemetry/anatomy.py, docs/
    # observability.md "Request anatomy"). All loop-stamped wall-time
    # sums that survive preemption (preempt() resets decode state but
    # never these), so the finish-time decomposition covers the
    # request's whole life: first-admission queue wait, per-life
    # prefill/decode wall, compile stall inside prefill, swap/stall
    # windows inside decode, and preempt->re-admit requeue time.
    # anat_compile_mark carries the profiler's compile-seconds total at
    # admission; anat_preempted_at the wall time of the last preempt
    # (0 = not currently preempted); anat_page_s the page-residency
    # integral (final page count x slot-resident wall, accumulated at
    # each preempt/finish).
    anat_queue_s: float = 0.0
    anat_prefill_s: float = 0.0
    anat_decode_s: float = 0.0
    anat_compile_s: float = 0.0
    anat_swap_s: float = 0.0
    anat_preempt_s: float = 0.0
    anat_page_s: float = 0.0
    anat_compile_mark: float = 0.0
    anat_preempted_at: float = 0.0

    @property
    def pos(self) -> int:
        """Next token position to be confirmed-written."""
        return len(self.tokens)

    def last_token(self) -> int:
        return self.tokens[-1]


def select_preemption_victim(candidates, max_preemptions: int):
    """Pure KV-pressure victim policy: lowest priority first, youngest
    (latest-submitted) on ties — the work with the least sunk cost and
    the weakest claim. Sequences at their preemption bound are exempt
    (they would otherwise live-lock re-prefilling forever), as are
    extract-mode sequences (disagg prefill workers: their one token is
    already sampled) and rows with a deferred finish in flight.

    Shared verbatim by the engine scheduler and the cluster simulator
    (``dynamo_exp_tpu/sim/``): ``candidates`` is any iterable of
    objects with the Sequence policy surface (``state``,
    ``pending_finish``, ``extract_cb``, ``preemptions``, ``priority``,
    ``submitted_at``). Returns None when nothing qualifies."""
    eligible = [
        s
        for s in candidates
        if s is not None
        and s.state is SeqState.ACTIVE
        and s.pending_finish is None
        and s.extract_cb is None
        and s.preemptions < max_preemptions
    ]
    if not eligible:
        return None
    return min(eligible, key=lambda s: (s.priority, -s.submitted_at))


class Scheduler:
    def __init__(self, cfg: EngineConfig, kv: KvPageManager, flight=None):
        self.cfg = cfg
        self.kv = kv
        self.waiting: deque[Sequence] = deque()
        self.slots: list[Sequence | None] = [None] * cfg.max_decode_slots
        self.active_count = 0  # PREFILL + ACTIVE (slot holders)
        # Flight recorder (telemetry/flight.py, engine-owned): finish /
        # preemption events land in the ring alongside the loop's
        # dispatch events. None = recording off.
        self.flight = flight
        # Set by the engine: () -> dict of dispatch-profiler attrs to
        # attach to the decode span (sim/fit.py fits from them).
        self.span_attrs: Callable[[], dict] | None = None
        # Set by the engine: (seq, reason, now, was_bound) -> None,
        # called at finish before page release — the request-anatomy
        # assembly tap (telemetry/anatomy.py).
        self.on_finish: Callable | None = None
        # Footprint-packed admission (docs/engine_perf.md "Predictive
        # KV tiering"): None = plain first-fit FIFO.
        self.forecast = KvFootprintForecast(kv, cfg) if cfg.kv_packing else None

    # --------------------------------------------------------------- intake
    def submit(self, seq: Sequence) -> None:
        self.waiting.append(seq)

    def reap_waiting(self, now: float | None = None) -> int:
        """Drop cancelled and deadline-expired sequences *anywhere* in
        the waiting deque — not just at the head — so dead requests
        neither inflate queue-depth gauges / admission bounds nor waste
        a prefill when their turn comes. Returns the number reaped."""
        if not self.waiting:
            return 0
        now = time.time() if now is None else now
        kept: list[Sequence] = []
        reaped = 0
        for seq in self.waiting:
            if seq.is_cancelled():
                seq.state = SeqState.FINISHED
                seq.emit([], FinishReason.CANCELLED)
                reaped += 1
            elif seq.deadline_unix and now >= seq.deadline_unix:
                # Mirror of the prefill worker's pre-compute drop (PR 2):
                # the client has already given up; admitting would burn a
                # slot and a prefill on undeliverable work.
                seq.state = SeqState.FINISHED
                get_telemetry().deadline_exceeded.labels(
                    "engine_admission"
                ).inc()
                seq.emit([], FinishReason.ERROR)
                reaped += 1
            else:
                kept.append(seq)
        if reaped:
            self.waiting = deque(kept)
        return reaped

    def has_work(self) -> bool:
        return self.active_count > 0 or bool(self.waiting)

    def free_slot(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def admit_next(self) -> Sequence | None:
        """Bind the next admissible waiting sequence to a slot + pages
        and put it in PREFILL state. Returns None if nothing can be
        admitted right now."""
        while self.waiting:
            if self.waiting[0].is_cancelled():
                seq = self.waiting.popleft()
                seq.state = SeqState.FINISHED
                seq.emit([], FinishReason.CANCELLED)
                continue
            head = self.waiting[0]
            if head.deadline_unix and time.time() >= head.deadline_unix:
                # The engine-loop reap is throttled; never let expired
                # work slip through admission in between scans.
                self.waiting.popleft()
                head.state = SeqState.FINISHED
                get_telemetry().deadline_exceeded.labels(
                    "engine_admission"
                ).inc()
                head.emit([], FinishReason.ERROR)
                continue
            slot = self.free_slot()
            if slot is None:
                return None
            seq = self._pick_admission()
            ps = self.kv.page_size
            if (
                len(seq.prompt) > self.cfg.max_model_len
                # A prompt needing more pages than the pool *has* can
                # never be allocated — reject instead of waiting forever.
                # Reachable from small prompts: a preempted sequence's
                # continuation prompt is its full generated context,
                # which can outgrow a pool smaller than max_model_len.
                or (len(seq.prompt) + ps - 1) // ps > self.kv.num_pages
                or self.cfg.bucket_for(
                    min(len(seq.prompt), self.cfg.prefill_chunk)
                )
                is None
            ):
                self._remove_waiting(seq)
                seq.state = SeqState.FINISHED
                seq.emit([], FinishReason.ERROR)
                continue
            alloc = self.kv.allocate_sequence(
                seq.prompt, self.cfg.max_pages_per_seq, seq.request_id
            )
            if alloc is None:
                return None  # pool exhausted; retry after some decode frees
            self._remove_waiting(seq)
            seq.page_ids, seq.cached_len = alloc.page_ids, alloc.cached_len
            seq.pending_uploads = alloc.uploads
            seq.prompt_hashes = alloc.hashes
            seq.wait_fill = list(alloc.wait_fill)
            seq.shared_tail_pid = (
                alloc.shared_tail[0] if alloc.shared_tail else -1
            )
            # Registered full pages this sequence resumes its hash chain
            # after (G1 matches + G2 uploads; never the partial tail —
            # that page registers under ITS OWN chain once this
            # sequence's tokens complete it post-COW).
            seq.hashed_pages = alloc.cached_pages
            seq.fill_marked = alloc.cached_pages
            seq.parent_hash = (
                alloc.hashes[seq.hashed_pages - 1] if seq.hashed_pages else None
            )
            self._register_uploads(seq, alloc.hashes)
            seq.tokens = list(seq.prompt)
            seq.prefill_sent = seq.cached_len
            seq.slot = slot
            seq.state = SeqState.PREFILL
            self.slots[slot] = seq
            self.active_count += 1
            return seq
        return None

    def _remove_waiting(self, seq: Sequence) -> None:
        """Drop one sequence from the waiting deque by identity (packed
        admission can pick past the head)."""
        for i, s in enumerate(self.waiting):
            if s is seq:
                del self.waiting[i]
                return

    def _pick_admission(self) -> Sequence:
        """The next sequence to try to admit: the head under plain
        first-fit, or — with footprint packing on — the first waiting
        sequence whose *lifetime* KV forecast fits the current
        free-page headroom (docs/engine_perf.md "Predictive KV
        tiering"). An oversize head that would be admitted only to
        hard-stall mid-decode defers behind smaller work; when nothing's
        forecast fits, the head is returned so packing never refuses an
        admission first-fit would have made. Priority and starvation
        guards live in :func:`~.tiering.select_packed_index`."""
        head = self.waiting[0]
        if self.forecast is None or len(self.waiting) == 1:
            return head
        headroom = self.forecast.headroom()
        cand = list(islice(self.waiting, self.cfg.packing_scan_limit))
        entries = [
            (
                self.forecast.forecast(s).fresh_pages <= headroom,
                s.priority,
                s.packing_defers,
            )
            for s in cand
        ]
        idx = select_packed_index(entries, self.cfg.packing_max_defers)
        if idx is None or idx == 0:
            return head
        for s in cand[:idx]:
            s.packing_defers += 1
        return cand[idx]

    def _register_uploads(self, seq: Sequence, hashes: list[int]) -> None:
        """Pages coming back from the host tier are about to be device-
        resident again: register them so G1 matching + the router index
        see them. Content lands at the inject dispatch (engine
        ``_apply_uploads``), so they register as pending fills — a
        same-prefix admission in between shares them but waits."""
        if not seq.pending_uploads:
            return
        ps = self.kv.page_size
        first = seq.hashed_pages - len(seq.pending_uploads)
        parent = hashes[first - 1] if first > 0 else None
        for j, (pid, seq_hash, _, _) in enumerate(seq.pending_uploads):
            i = first + j
            block = seq.prompt[i * ps : (i + 1) * ps]
            self.kv.register_full_page(
                pid, seq_hash, parent_hash=parent, tokens=block,
                content_ready=False,
            )
            self.kv.begin_fill(pid, seq.request_id)
            parent = seq_hash

    # --------------------------------------------------------- fill gating
    def fill_ready(self, seq: Sequence) -> bool:
        """True once every attached shared page this sequence depends on
        has had its fill dispatched. Orphans (filler died first) are
        claimed here: this sequence re-fills fully covered blocks itself
        (identical content by determinism); an orphaned partial tail is
        detached onto a fresh private page instead — other holders may
        still need the original."""
        if not seq.wait_fill:
            return True
        ps = self.kv.page_size
        still: list[int] = []
        for pid in seq.wait_fill:
            state = self.kv.fill_state(pid)
            if state == "filled":
                continue
            if state == "pending":
                still.append(pid)
                continue
            # Orphaned: adopt or detach.
            idx = seq.page_ids.index(pid)
            if (idx + 1) * ps <= len(seq.prompt) and pid != seq.shared_tail_pid:
                self.kv.claim_fill(pid, seq.request_id)
                seq.prefill_sent = min(seq.prefill_sent, idx * ps)
                seq.cached_len = min(seq.cached_len, idx * ps)
                seq.fill_marked = min(seq.fill_marked, idx)
            else:
                fresh = self.kv.allocate_page()
                if fresh is None:
                    still.append(pid)  # pool dry: retry next iteration
                    continue
                seq.page_ids[idx] = fresh
                self.kv.release_sequence([pid])
                seq.shared_tail_pid = -1
                seq.prefill_sent = min(seq.prefill_sent, idx * ps)
                seq.cached_len = min(seq.cached_len, idx * ps)
        seq.wait_fill = still
        return not still

    # ------------------------------------------------------------- lifecycle
    def ensure_pages_until(self, seq: Sequence, position: int) -> bool:
        """Before a decode window writes up to ``position`` (inclusive):
        allocate every page the window will cross into. Returns False if
        the pool runs dry (the sequence sits this window out); pages
        allocated before the dry pop stay bound to the sequence, so the
        next attempt only needs the remainder."""
        ps = self.kv.page_size
        need = min(position, self.cfg.max_model_len - 1) // ps + 1
        while len(seq.page_ids) < need:
            pid = self.kv.allocate_page()
            if pid is None:
                return False
            seq.page_ids.append(pid)
        return True

    def register_full_pages(self, seq: Sequence) -> None:
        """Register every newly completed page for reuse + router events.

        Only positions up to ``pos - 1`` have KV written (the newest
        sampled token's KV lands on the next step), hence the -1."""
        ps = self.kv.page_size
        full = (seq.pos - 1) // ps
        while seq.hashed_pages < full:
            i = seq.hashed_pages
            block = seq.tokens[i * ps : (i + 1) * ps]
            if i < len(seq.prompt_hashes):
                # Pure-prompt page: the chained hash was already computed
                # at allocation; decode-era pages hash incrementally.
                seq_hash = seq.prompt_hashes[i]
            else:
                local = compute_block_hash(block)
                seq_hash = chain_hash(seq.parent_hash, local)
            self.kv.register_full_page(
                seq.page_ids[i], seq_hash, parent_hash=seq.parent_hash, tokens=block
            )
            seq.parent_hash = seq_hash
            seq.hashed_pages += 1

    def finish(self, seq: Sequence, reason: FinishReason) -> None:
        if seq.state == SeqState.FINISHED:
            return
        now = time.time()
        was_bound = seq.state in (SeqState.PREFILL, SeqState.ACTIVE)
        if seq.first_token_at and seq.extract_cb is None:
            # Close the request's decode span (first token -> finish).
            # Extract-mode sequences (disagg prefill workers) never
            # decode — their work ends at the first token.
            # Runs on the engine loop thread, so the trace context is
            # the one captured at submission, not a contextvar.
            get_telemetry().emit_stage(
                "decode",
                seq.first_token_at,
                now,
                seq.trace,
                generated_tokens=seq.generated,
                finish_reason=getattr(reason, "value", str(reason)),
                spec_tokens_per_dispatch=(
                    round(seq.spec_emitted_tokens / seq.spec_dispatches, 4)
                    if seq.spec_dispatches
                    else None
                ),
                pages=len(seq.page_ids),
                priority=seq.priority,
                swap_stall_s=(
                    round(seq.anat_swap_s, 6) if seq.anat_swap_s else None
                ),
                **(self.span_attrs() if self.span_attrs is not None else {}),
            )
        if self.flight is not None:
            self.flight.record(
                "finish",
                req=seq.request_id,
                slot=seq.slot if was_bound else None,
                reason=getattr(reason, "value", str(reason)),
                generated=seq.generated,
                pages=len(seq.page_ids),
                priority=seq.priority,
            )
        # Anatomy hook (engine._record_anatomy): runs before page
        # release so the page count is still real, with the same
        # ``now`` the decode span closed on.
        if self.on_finish is not None:
            self.on_finish(seq, reason, now, was_bound)
        seq.state = SeqState.FINISHED
        if seq.slot >= 0 and was_bound:
            self.slots[seq.slot] = None
            self.active_count -= 1
            seq.slot = -1
        # Fills this sequence owed but never dispatched orphan first so
        # sharers can claim them; THEN the refs drop (a zero-ref
        # unfilled page unregisters instead of parking as matchable).
        self.kv.abort_fills(seq.request_id, seq.page_ids)
        self.kv.release_sequence(seq.page_ids)
        seq.emit([], reason)

    # ------------------------------------------------------------ preemption
    def preemption_victim(self, max_preemptions: int) -> Sequence | None:
        """The sequence KV-pressure preemption evicts next (policy in
        :func:`select_preemption_victim`, shared with the simulator)."""
        return select_preemption_victim(self.slots, max_preemptions)

    def preempt(self, seq: Sequence) -> None:
        """Unbind an ACTIVE sequence from its slot, release its pages,
        and requeue it as a deterministic continuation of itself.

        The released *registered* pages park in the reclaimable LRU
        (write-back to the host offload tier on eviction), so a prompt
        re-admission soon after usually prefix-hits most of its own
        context. The continuation re-enters as a fresh request whose
        prompt is the full generated context; counter-based sampling —
        every draw keyed by (seed, absolute position) — makes the
        resumed stream token-identical to the uninterrupted run, so the
        client-facing SSE stream stays gapless (the continuation emits
        only tokens past the splice). Requeues at the *back* of the
        waiting deque: re-admitting immediately would revive the pages
        just parked and starve the stalled rows the preemption was
        meant to feed."""
        k = seq.generated
        now = time.time()
        # Anatomy: close this life's decode segment and any open swap /
        # stall window, book the page-residency integral for the pages
        # about to be released, and mark preemption limbo — requeue
        # time until re-admission (or finish) counts as ``preemption``.
        if seq.first_token_at:
            seq.anat_decode_s += max(now - seq.first_token_at, 0.0)
        elif seq.admitted_at:
            seq.anat_prefill_s += max(now - seq.admitted_at, 0.0)
        if seq.swapped_since:
            seq.anat_swap_s += max(now - seq.swapped_since, 0.0)
        elif seq.stalled_since:
            seq.anat_swap_s += max(now - seq.stalled_since, 0.0)
        if seq.admitted_at:
            seq.anat_page_s += len(seq.page_ids) * max(
                now - seq.admitted_at, 0.0
            )
        seq.anat_preempted_at = now
        if self.flight is not None:
            self.flight.record(
                "preempt",
                req=seq.request_id,
                slot=seq.slot,
                generated=k,
                freed_pages=len(seq.page_ids),
            )
        if seq.slot >= 0:
            self.slots[seq.slot] = None
            self.active_count -= 1
            seq.slot = -1
        self.kv.abort_fills(seq.request_id, seq.page_ids)
        self.kv.release_sequence(seq.page_ids)
        seq.page_ids = []
        stop = seq.stop.model_copy(deep=True)
        sc = stop.stop_conditions
        orig_max = (
            sc.max_tokens
            if sc.max_tokens is not None
            else self.cfg.default_max_tokens
        )
        sc.max_tokens = max(orig_max - k, 1)
        if sc.min_tokens:
            sc.min_tokens = max(sc.min_tokens - k, 0)
        # Cumulative across preemptions: ``resume_offset`` marks how much
        # of the new prompt is journaled *completion* tokens, so the
        # sampler's penalty counts rebuild over all of them at re-prefill
        # (engine._finish_first_token).
        stop.resume_offset = (seq.stop.resume_offset or 0) + k
        stop.token_ids = list(seq.tokens)
        seq.stop = stop
        seq.prompt = list(seq.tokens)
        seq.tokens = []
        seq.generated = 0
        seq.prefill_sent = 0
        seq.cached_len = 0
        seq.stalled = False
        seq.stalled_since = 0.0
        seq.pending_finish = None
        seq.pending_uploads = []
        seq.wait_fill = []
        seq.shared_tail_pid = -1
        seq.fill_marked = 0
        seq.prompt_hashes = []
        seq.hashed_pages = 0
        seq.parent_hash = None
        seq.remote_kv = None
        seq.remote_prefilled = False
        # Tiering state: the continuation's prompt is new (forecast
        # hashes stale), its queue history resets, and any swap record
        # dies with the old page table (host-tier entries it referenced
        # simply age out of the LRU as unmatched cache).
        seq.forecast_hashes = None
        seq.packing_defers = 0
        seq.swap = None
        seq.swapped_since = 0.0
        seq.preemptions += 1
        seq.state = SeqState.WAITING
        self.waiting.append(seq)

    # -------------------------------------------------------------- stopping
    def check_stop(self, seq: Sequence, token: int) -> FinishReason | None:
        sc = seq.stop.stop_conditions
        min_tokens = sc.min_tokens or 0
        if seq.generated >= min_tokens:
            if not sc.ignore_eos and (
                token in self.cfg.eos_token_ids or token in sc.stop_token_ids
            ):
                return FinishReason.EOS
        max_tokens = sc.max_tokens or self.cfg.default_max_tokens
        if seq.generated >= max_tokens:
            return FinishReason.LENGTH
        if seq.pos >= self.cfg.max_model_len:
            return FinishReason.LENGTH
        return None

    # --------------------------------------------------------------- metrics
    def metrics(self) -> dict:
        """ForwardPassMetrics equivalent (reference:
        ``lib/llm/src/kv_router/protocols.rs:43-55``)."""
        running = sum(
            1 for s in self.slots if s is not None and s.state is SeqState.ACTIVE
        )
        return {
            "request_active_slots": self.active_count,
            "request_total_slots": self.cfg.max_decode_slots,
            "request_stalled_slots": sum(
                1 for s in self.slots if s is not None and s.stalled
            ),
            # Proactive offload (docs/engine_perf.md "Predictive KV
            # tiering"): ACTIVE rows whose cold pages currently live in
            # the host tier, awaiting swap-in.
            "request_swapped_slots": sum(
                1 for s in self.slots if s is not None and s.swap is not None
            ),
            "kv_active_blocks": self.kv.active_pages,
            "kv_total_blocks": self.kv.num_pages,
            "num_requests_waiting": len(self.waiting),
            "gpu_cache_usage_perc": self.kv.usage,
            "gpu_prefix_cache_hit_rate": self.kv.hit_rate(),
            # Engine-level gauges (telemetry): scheduler depth and decode
            # batch fill; the KV-tier gauges ride in via kv.gauges().
            "num_requests_running": running,
            "decode_batch_utilization": running / max(self.cfg.max_decode_slots, 1),
            **self.kv.gauges(),
        }
