"""The tuned-config artifact ``llmctl tune`` emits (docs/tuning.md).

One JSON file carries everything a deployment needs to *boot* the
recommendation, not just read it:

- the winning knob **overrides** plus the fully resolved live engine
  knob dict and its stable ``config_hash`` — the same hash bench lines
  are stamped with, so a tuned run's bench capture pairs against the
  right baseline by construction;
- **provenance**: target fingerprint digest, search seed, objective
  scores, trial count, and the knob-space digest the search ran over
  (an artifact from a stale registry is detectable, not silently
  misapplied);
- the target **fingerprint** itself (when the target was one), which
  is what turns the artifact into a planner
  :class:`~dynamo_exp_tpu.planner.policy.CatalogEntry`;
- the matching AOT **CompileManifest**, so booting from the artifact
  is also a zero-compile warm boot (docs/aot.md);
- the sim-vs-live **validation** verdict, when the validation stage
  ran.
"""

from __future__ import annotations

import json

from . import space

ARTIFACT_VERSION = 1


def resolved_live_knobs(overrides: dict) -> dict:
    """The full live engine knob dict the overrides resolve to:
    registry defaults overlaid with the engine-owner overrides. This —
    not the sparse overrides — is what ``config_hash`` covers, so two
    artifacts that resolve to the same engine agree on hash even if
    one spells a default explicitly."""
    out = {}
    for k in space.KNOBS:
        if k.owner == "engine" and k.live:
            out[k.name] = overrides.get(k.name, space.default_value(k))
    return out


def build_artifact(
    result,
    *,
    preset: str = "tiny",
    shape: dict | None = None,
    manifest=None,
    fingerprint=None,
    validation: dict | None = None,
) -> dict:
    """Assemble the artifact dict from a :class:`~.search.TuneResult`.
    ``shape`` is the non-tuned engine envelope (max_model_len,
    kv_dtype, tp, spec_mode) the deployment pins; ``manifest`` the
    matching :class:`~dynamo_exp_tpu.aot.CompileManifest`."""
    knobs = resolved_live_knobs(result.best_overrides)
    art = {
        "version": ARTIFACT_VERSION,
        "overrides": {
            k: result.best_overrides[k] for k in sorted(result.best_overrides)
        },
        "config_hash": space.config_hash(knobs),
        "provenance": {
            "target": result.target_digest,
            "seed": result.seed,
            "objective": "goodput_per_chip_s * ttft_ok * itl_ok",
            "trials": result.trials,
            "space": space.space_digest(),
            "best_score": result.best_score,
            "default_score": result.default_score,
            "improvement": result.improvement,
        },
        "engine": {
            "preset": preset,
            "shape": dict(shape or {}),
            "knobs": knobs,
        },
        "fingerprint": (
            fingerprint.to_dict() if fingerprint is not None else None
        ),
        "validation": validation,
        "manifest": manifest.to_dict() if manifest is not None else None,
    }
    return art


def write_artifact(art: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(art, f, indent=2, sort_keys=True)
        f.write("\n")


def load_artifact(path: str) -> dict:
    with open(path) as f:
        art = json.load(f)
    if art.get("version") != ARTIFACT_VERSION:
        raise ValueError(
            f"unsupported tune artifact version {art.get('version')!r} "
            f"in {path} (expected {ARTIFACT_VERSION})"
        )
    return art


def engine_config_from_artifact(art: dict, model=None):
    """Boot config: preset model + pinned shape + the artifact's fully
    resolved engine knobs. ``model`` overrides the preset lookup (tests
    pass TINY directly)."""
    from ..engine import EngineConfig

    if model is None:
        from ..models import PRESETS

        model = PRESETS[art["engine"]["preset"]]
    kwargs = dict(art["engine"]["shape"])
    kwargs.update(art["engine"]["knobs"])
    kwargs.setdefault("eos_token_ids", [])
    return EngineConfig(model=model, **kwargs)


def manifest_from_artifact(art: dict):
    if art.get("manifest") is None:
        return None
    from ..aot import CompileManifest

    return CompileManifest.from_dict(art["manifest"])


def catalog_entry_from_artifact(art: dict, name: str = ""):
    """Turn the artifact into a planner catalog entry. Requires the
    artifact to carry its target fingerprint — a synthetic-target
    artifact has nothing for the drift comparison to key on."""
    from ..planner.policy import CatalogEntry
    from ..telemetry.fingerprint import WorkloadFingerprint

    if art.get("fingerprint") is None:
        raise ValueError(
            "tune artifact has no target fingerprint; only "
            "fingerprint-targeted artifacts can join a config catalog"
        )
    return CatalogEntry(
        name=name or art["provenance"]["target"],
        fingerprint=WorkloadFingerprint.from_dict(art["fingerprint"]),
        overrides=tuple(sorted(art["overrides"].items())),
        config_hash=art["config_hash"],
    )
