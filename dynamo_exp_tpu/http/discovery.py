"""Ingress model discovery: watch ``models/``, build serving chains.

Capability parity with the reference's ModelWatcher
(``/root/reference/lib/llm/src/http/service/discovery.rs:100-340``): on a
new ModelEntry, fetch the ModelDeploymentCard from the object store and
register a preprocessor→backend→router chain with the ModelManager; on
removal (lease expiry = worker death), drop the model.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

from ..local_model import MDC_BUCKET, MODELS_PREFIX, ModelEntry
from ..model_card import ModelDeploymentCard
from ..runtime.component import DistributedRuntime
from ..runtime.push_router import RouterMode
from ..runtime.transports.base import EndpointAddress
from .service import ModelManager, build_pipeline_engine

logger = logging.getLogger(__name__)


class ModelWatcher:
    """Keeps a ModelManager in sync with the discovery KV's ``models/``."""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        router_mode: RouterMode = RouterMode.RANDOM,
    ):
        self.drt = drt
        self.manager = manager
        self.router_mode = router_mode
        # kv key -> (model name, model_type): registrations are
        # type-scoped (a name can be chat-only, completion-only, or
        # both via separate entries — e.g. llmctl's per-type keys).
        self._active: dict[str, tuple[str, str]] = {}
        self._task: asyncio.Task | None = None
        # Chains/routers are keyed by the serving identity — (name,
        # endpoint, mdc_key) — NOT by name alone: one name's chat and
        # completion entries may point at different endpoints (different
        # workers), and each type's traffic must ride its own entry's
        # chain.
        self._kv_routers: dict[tuple, object] = {}
        self._chains: dict[tuple, object] = {}

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._watch())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        for r in self._kv_routers.values():
            await r.stop()
        self._kv_routers.clear()

    async def _watch(self) -> None:
        # The watch stream itself can break (coordinator hiccup); an
        # ingress must re-establish it, not freeze its model set.
        while True:
            try:
                async for snapshot in self.drt.discovery.kv_watch_prefix(
                    MODELS_PREFIX
                ):
                    try:
                        await self._apply(snapshot)
                    except Exception:  # noqa: BLE001 - keep watching
                        logger.exception("model watch apply failed")
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - reconnect after backoff
                logger.exception("model watch stream broke; retrying")
                await asyncio.sleep(1.0)

    @staticmethod
    def _types_of(model_type: str) -> set[str]:
        return {"chat", "completion"} if model_type == "both" else {model_type}

    def _covered_types(self, name: str) -> set[str]:
        """Types currently provided for ``name`` by active entries."""
        out: set[str] = set()
        for n, t in self._active.values():
            if n == name:
                out |= self._types_of(t)
        return out

    async def _apply(self, snapshot: dict[str, bytes]) -> None:
        removed_keys = [k for k in self._active if k not in snapshot]
        for key in removed_keys:
            name, mtype = self._active.pop(key)
            # N replicas write N keys for one model; drop each type
            # only when the *last* entry providing it is gone.
            still = self._covered_types(name)
            gone = self._types_of(mtype) - still
            if "chat" in gone:
                self.manager.remove_chat_model(name)
            if "completion" in gone:
                self.manager.remove_completion_model(name)
            if not still:
                logger.info("model %s removed (last worker gone)", name)
        if removed_keys:
            # Chains/routers whose serving identity no longer has any
            # live entry must stop — including when only ONE type of a
            # name died and its identity differs from the survivor's
            # (leaving it would scrape a dead endpoint forever).
            live = set()
            for k, (name, _) in self._active.items():
                raw = snapshot.get(k)
                if raw is None:
                    continue
                try:
                    e = ModelEntry.from_bytes(raw)
                except Exception:  # noqa: BLE001
                    continue
                live.add((e.name, e.endpoint, e.mdc_key))
            for ck in [k for k in self._chains if k not in live]:
                del self._chains[ck]
            for rk in [k for k in self._kv_routers if k not in live]:
                router = self._kv_routers.pop(rk)
                await router.stop()  # drop its event sub + scrape loop
        for key, raw in snapshot.items():
            if key in self._active:
                continue
            # Per-entry guard: one bad entry (missing MDC, unreadable
            # tokenizer path) must not block its siblings.
            try:
                entry = ModelEntry.from_bytes(raw)
                new_types = self._types_of(entry.model_type) - self._covered_types(
                    entry.name
                )
                if new_types:
                    # First entry for this (name, type): build — or
                    # reuse — the chain for this entry's serving
                    # identity. The chain's client watches every live
                    # instance of the endpoint, so later replicas of
                    # the same endpoint ride it too.
                    ck = (entry.name, entry.endpoint, entry.mdc_key)
                    engine = self._chains.get(ck)
                    if engine is None:
                        engine = await self._build_chain(entry)
                        self._chains[ck] = engine
                    if "chat" in new_types:
                        self.manager.add_chat_model(entry.name, engine)
                    if "completion" in new_types:
                        self.manager.add_completion_model(entry.name, engine)
                    logger.info(
                        "model %s (%s) registered via %s",
                        entry.name, entry.model_type, entry.endpoint,
                    )
                self._active[key] = (entry.name, entry.model_type)
            except Exception:  # noqa: BLE001 - retried on next KV change
                logger.exception("failed to register model entry %s", key)

    async def _build_chain(self, entry: ModelEntry):
        raw = await self.drt.object_store.get(MDC_BUCKET, entry.mdc_key)
        if raw is None:
            raise RuntimeError(f"no MDC in object store for {entry.name}")
        mdc = ModelDeploymentCard.from_json(raw.decode())
        addr = EndpointAddress.from_url(entry.endpoint)
        ep = (
            self.drt.namespace(addr.namespace)
            .component(addr.component)
            .endpoint(addr.name)
        )
        from ..kv_router.router import build_routed_core

        core, kv_router = await build_routed_core(
            ep, self.router_mode, mdc.kv_cache_block_size
        )
        if kv_router is not None:
            # A retry after a partially-failed registration may rebuild
            # the chain; stop the superseded router or it scrapes forever.
            rk = (entry.name, entry.endpoint, entry.mdc_key)
            old = self._kv_routers.pop(rk, None)
            if old is not None:
                await old.stop()
            self._kv_routers[rk] = kv_router
        return build_pipeline_engine(mdc, core)
