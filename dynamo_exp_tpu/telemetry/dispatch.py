"""Per-dispatch device profiling (docs/observability.md "Per-dispatch
device profiling").

The engine loop issues three kinds of device dispatch — ragged compute
batches (pure-decode windows and mixed prefill+decode+spec batches
alike, docs/engine_perf.md "One ragged dispatch"), KV page
gather/scatter moves, and eviction offload batches — and in the
overlapped steady state (docs/engine_perf.md) a throughput problem is
always one of two things: the device spent too long *in flight*, or
the host left a *gap* between consuming one dispatch and issuing the
next. This profiler attributes wall time to exactly those two buckets
per dispatch kind, plus compiled-variant cache behavior, using nothing
but ``time.monotonic()`` timestamps taken at call sites the engine
already owns:

- ``begin(kind)`` immediately before the dispatch call records the
  **host gap** since the kind's previous consume (or previous dispatch,
  for kinds that are never host-synced) and returns the timestamp;
- ``end(kind, t0, fresh)`` right after the dispatch call returns stamps
  dispatch completion (and, for a fresh compiled variant, attributes
  the call's wall time — trace + compile + program load — to
  ``dynamo_compile_seconds{kind}``);
- ``consume(kind, t_dispatch)`` right after the *already-present* host
  sync (the ``np.asarray`` the engine was going to do anyway) records
  the **in-flight** time.

Nothing here blocks, syncs, or touches the device: the overhead
guarantee is *zero additional host syncs per dispatch* (asserted by the
sync-spy smoke test in tests/test_dispatch_profile.py), and the overlap
semantics the recompile-guard / chained-decode identity suites police
are untouched. In the chained steady state the host gap collapses
toward zero — which is precisely the signal: a growing gap under
overlap means the host loop, not the device, is the bottleneck (APEX,
arxiv 2506.03296).

Samples live in bounded per-kind reservoirs (recent-window deques), so
``summary()`` percentiles are cheap and memory is O(1); lifetime totals
ride the prometheus histograms.
"""

from __future__ import annotations

import time
from collections import deque

from .slo import percentile

# The engine's three device-dispatch kinds. Stable, closed set: the
# prometheus label space, the metrics() mirror, and bench.py's per-kind
# percentiles all key on these names. ``ragged`` covers every compute
# dispatch (the pre-ragged engine split it into prefill / decode /
# spec_verify; sim/fit.py still reads those names from old span and
# bench files for back-compat).
DISPATCH_KINDS = ("ragged", "kv_move", "offload")

# Summary stat fields (also the bench JSON / docs contract).
SUMMARY_FIELDS = (
    "count",
    "host_gap_p50_s",
    "host_gap_p99_s",
    "in_flight_p50_s",
    "in_flight_p99_s",
    "compile_misses",
    "compile_total_s",
)


class DispatchProfiler:
    """Host-side per-dispatch timing. All methods are cheap (two clock
    reads and a deque append at worst) and rely on the GIL for the
    cross-thread case (the offload consume arrives from the CopyStream
    thread; ``summary()`` may be called from any thread)."""

    def __init__(self, telemetry=None, reservoir: int = 1024):
        self._tel = telemetry
        self._gap = {k: deque(maxlen=reservoir) for k in DISPATCH_KINDS}
        self._flight = {k: deque(maxlen=reservoir) for k in DISPATCH_KINDS}
        self._count = dict.fromkeys(DISPATCH_KINDS, 0)
        self._compile_misses = dict.fromkeys(DISPATCH_KINDS, 0)
        self._compile_s = dict.fromkeys(DISPATCH_KINDS, 0.0)
        # kind -> (monotonic time, event seq) of the last consume (or
        # dispatch end, for never-synced kinds); cleared on idle so
        # gaps never span a genuinely-idle engine. The event seq gates
        # gap recording: if ANY other profiler event landed in between,
        # the engine was busy with other dispatch kinds and the elapsed
        # time is work inter-arrival, not host overhead — a prefill
        # arriving 5s into a decode-saturated steady state must not
        # read as a 5s prefill host gap.
        self._last_consume: dict[str, tuple[float, int]] = {}
        self._event_seq = 0
        # (family, key) variants already seen — freshness for compiled
        # caches that live inside a single jax.jit (the page-move
        # kernels key variants by bucket shape, invisibly to the
        # engine-level fn caches).
        self._seen_variants: set = set()

    # ------------------------------------------------------------ dispatch
    def begin(self, kind: str) -> float:
        """Immediately before the dispatch call; returns its timestamp
        (pass to :meth:`end` and stash for :meth:`consume`). The host
        gap since the kind's previous consume is recorded only when no
        other dispatch activity intervened — back-to-back work of the
        same kind, the case where the elapsed time really is host
        overhead."""
        now = time.monotonic()
        last = self._last_consume.get(kind)
        self._event_seq += 1
        if last is not None and last[1] == self._event_seq - 1:
            gap = max(now - last[0], 0.0)
            self._gap[kind].append(gap)
            if self._tel is not None:
                self._tel.host_gap_seconds.labels(kind).observe(gap)
        return now

    def end(self, kind: str, t0: float, fresh: bool = False) -> float:
        """Immediately after the dispatch call returns. ``fresh`` marks
        a compiled-variant cache miss: the call's wall time is the
        first-compile duration (jit traces/compiles synchronously inside
        the call; steady-state calls only enqueue). Returns the
        dispatch-completion timestamp for :meth:`consume`."""
        now = time.monotonic()
        self._count[kind] += 1
        if fresh:
            dur = max(now - t0, 0.0)
            self._compile_misses[kind] += 1
            self._compile_s[kind] += dur
            if self._tel is not None:
                self._tel.compile_cache_misses.labels(kind).inc()
                self._tel.compile_seconds.labels(kind).observe(dur)
        # Never-synced kinds (scatter moves) get their gap reference
        # here; synced kinds overwrite it with the later consume.
        self._event_seq += 1
        self._last_consume[kind] = (now, self._event_seq)
        return now

    def first_variant(self, family: str, key) -> bool:
        """True exactly once per (family, key): compile-miss detection
        for variant caches the engine can't watch by size (jit-internal
        shape keys)."""
        k = (family, key)
        if k in self._seen_variants:
            return False
        self._seen_variants.add(k)
        return True

    def seed_variants(self, family: str, keys) -> None:
        """Warm-boot seeding (docs/aot.md): mark (family, key) variants
        as already-compiled so their first *traffic* dispatch is never
        charged as a cold compile. The freshness heuristics predate
        prewarm — without this, a prewarmed gather/scatter bucket's
        first live dispatch would read as a miss and break the
        flat-from-first-dispatch guarantee the prewarm-smoke gate
        asserts. (The engine's ragged cache needs no seeding: its
        freshness is a cache-size delta, and prewarm populates the
        cache itself.)"""
        for key in keys:
            self._seen_variants.add((family, key))

    # ------------------------------------------------------------- consume
    def consume(self, kind: str, t_dispatch: float) -> None:
        """Immediately after the dispatch's existing host sync. Records
        in-flight time and arms the kind's host-gap reference."""
        now = time.monotonic()
        if t_dispatch > 0.0:
            flight = max(now - t_dispatch, 0.0)
            self._flight[kind].append(flight)
            if self._tel is not None:
                self._tel.dispatch_seconds.labels(kind).observe(flight)
        self._event_seq += 1
        self._last_consume[kind] = (now, self._event_seq)

    def mark_idle(self) -> None:
        """The loop is parking (no work, or everything stalled): drop
        the gap references so wait time never reads as host gap."""
        self._last_consume.clear()

    def compile_total_s(self) -> float:
        """Cumulative fresh-compile seconds across all kinds. The
        request-anatomy tap marks this at admission and attributes the
        delta at first token as the request's compile stall."""
        return sum(self._compile_s.values())

    def host_gap_fraction(self, kind: str) -> float:
        """Median host-gap share of one dispatch interval for ``kind``
        (gap / (gap + in-flight)), in [0, 1]. The anatomy decomposition
        uses it to carve host_gap out of decode compute. 0.0 before the
        first sample."""
        flight = self._p(self._flight[kind], 0.5)
        if flight is None:
            return 0.0
        gap = self._p(self._gap[kind], 0.5) or 0.0
        return gap / (gap + flight) if (gap + flight) > 0 else 0.0

    # ------------------------------------------------------------- summary
    @staticmethod
    def _p(samples, q) -> float | None:
        v = percentile(list(samples), q)
        return round(v, 6) if v is not None else None

    def summary(self) -> dict:
        """Per-kind stats over the recent reservoir window — the
        ``engine.metrics()["dispatch"]`` mirror and bench.py's per-line
        dispatch field. Every kind is always present (count 0, None
        percentiles before its first dispatch) so consumers see a
        stable shape."""
        out = {}
        for k in DISPATCH_KINDS:
            out[k] = {
                "count": self._count[k],
                "host_gap_p50_s": self._p(self._gap[k], 0.5),
                "host_gap_p99_s": self._p(self._gap[k], 0.99),
                "in_flight_p50_s": self._p(self._flight[k], 0.5),
                "in_flight_p99_s": self._p(self._flight[k], 0.99),
                "compile_misses": self._compile_misses[k],
                "compile_total_s": round(self._compile_s[k], 6),
            }
        return out

    def span_attrs(self, kind: str, **extra) -> dict:
        """Attrs for the existing decode/prefill spans (sim/fit.py fits
        per-dispatch service times from these): median in-flight and
        host-gap for the kind, or {} before the first sample."""
        flight = self._p(self._flight[kind], 0.5)
        if flight is None:
            return {}
        gap = self._p(self._gap[kind], 0.5)
        return {
            "dispatch_p50_s": flight,
            "host_gap_p50_s": gap if gap is not None else 0.0,
            **extra,
        }
