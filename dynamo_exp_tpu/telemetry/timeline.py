"""Offline trace reconstruction from recorder JSONL (``llmctl trace``).

Spans land in the recorder as ``{"ts": ..., "event": {"type": "span",
...}}`` lines, possibly interleaved across stages, processes, and file
rotations. This module loads them back, groups by ``trace_id``, rebuilds
the parent/child tree, and renders an ASCII timeline.
"""

from __future__ import annotations

import glob
import os
import re

from ..recorder import Recorder
from .spans import Span


def load_spans(paths: list[str]) -> list[Span]:
    """Read every span event from the given JSONL files.

    For each path, recorder siblings are read too: rotated generations
    (``path.N``), per-process recordings from a shared
    ``DYN_TRACE_FILE`` (``path.pid<pid>``), and their rotations
    (``path.pid<pid>.N``). Unrelated siblings (``path.1.bak``,
    ``path.1.gz``) are skipped, not crashed on. Ordering across files
    doesn't matter — spans carry absolute timestamps.
    """
    gen_re = re.compile(r"^(\.pid\d+)?(\.\d+)*$")

    def _is_generation(cand: str, base: str) -> bool:
        suffix = cand[len(base) :]
        return bool(suffix) and gen_re.fullmatch(suffix) is not None

    spans: list[Span] = []
    seen: set[str] = set()
    expanded: list[str] = []
    for p in paths:
        siblings = sorted(
            c
            for c in glob.glob(p + ".*")
            if _is_generation(c, p)
        )
        for cand in siblings + [p]:
            if cand not in seen and os.path.exists(cand):
                seen.add(cand)
                expanded.append(cand)
    for path in expanded:
        for _ts, event in Recorder.replay(path):
            if isinstance(event, dict) and event.get("type") == "span":
                spans.append(Span.from_event(event))
    return spans


def find_trace(spans: list[Span], needle: str) -> list[Span]:
    """Spans of the trace identified by ``needle``: a full or prefix
    trace_id, or a request id recorded in any span's attrs."""
    by_trace: dict[str, list[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    if needle in by_trace:
        return by_trace[needle]
    for tid, group in by_trace.items():
        if tid.startswith(needle):
            return group
    for tid, group in by_trace.items():
        if any(s.attrs.get("request_id") == needle for s in group):
            return group
    return []


def _order_tree(spans: list[Span]) -> list[tuple[Span, int]]:
    """(span, depth) in tree order: children under parents, siblings by
    start time. Orphans (parent span missing, e.g. a lost process's
    file) surface at the root level instead of disappearing."""
    by_id = {s.span_id: s for s in spans}
    children: dict[str, list[Span]] = {}
    roots: list[Span] = []
    for s in spans:
        if s.parent_span_id and s.parent_span_id in by_id:
            children.setdefault(s.parent_span_id, []).append(s)
        else:
            roots.append(s)
    out: list[tuple[Span, int]] = []

    def walk(span: Span, depth: int) -> None:
        out.append((span, depth))
        for c in sorted(children.get(span.span_id, []), key=lambda x: x.start):
            walk(c, depth + 1)

    for r in sorted(roots, key=lambda x: x.start):
        walk(r, 0)
    return out


def render_timeline(spans: list[Span], width: int = 40) -> str:
    """Human-readable span tree with offset/duration bars::

        trace 4f1f2a… — 6 spans, 132.8ms total
        http_request          0.0ms  132.8ms |##############################|
          preprocess          0.3ms    1.9ms |=                             |
          ...

    A trace whose spans come from more than one instance (a stitched
    disagg/failover trace) renders as a multi-instance timeline: each
    span's instance shows in its own column, and the cross-instance KV
    transfer hops are summarized (per-hop duration, bytes, MB/s) after
    the tree — the traced view of what the TransferLedger aggregates.
    """
    if not spans:
        return "no spans"
    t0 = min(s.start for s in spans)
    t1 = max(s.end for s in spans)
    total = max(t1 - t0, 1e-9)
    ordered = _order_tree(spans)
    name_w = max(len("  " * d + s.stage) for s, d in ordered)
    req = next(
        (s.attrs["request_id"] for s, _ in ordered if "request_id" in s.attrs),
        None,
    )
    instances = sorted(
        {str(s.attrs["instance"]) for s in spans if s.attrs.get("instance")}
    )
    multi = len(instances) > 1
    inst_w = max((len(i) for i in instances), default=0) if multi else 0
    head = f"trace {spans[0].trace_id} — {len(spans)} spans, {total * 1e3:.1f}ms"
    if req:
        head += f" (request {req})"
    if multi:
        head += f" across {len(instances)} instances"
    lines = [head]
    for s, depth in ordered:
        off = s.start - t0
        left = int(round((off / total) * width))
        fill = max(int(round((s.duration_s / total) * width)), 1)
        fill = min(fill, width - min(left, width - 1))
        bar = " " * min(left, width - 1) + "#" * fill
        bar = bar[:width].ljust(width)
        label = ("  " * depth + s.stage).ljust(name_w)
        inst = (
            f" [{str(s.attrs.get('instance', '?')):<{inst_w}}]" if multi else ""
        )
        lines.append(
            f"{label}{inst}  {off * 1e3:8.1f}ms {s.duration_s * 1e3:9.1f}ms "
            f"|{bar}|"
        )
        extra = {
            k: v
            for k, v in s.attrs.items()
            if k not in ("request_id", "instance")
        }
        if extra:
            kv = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
            lines.append(" " * (name_w + 2) + f"  {kv}")
    hops = transfer_hops(spans)
    if hops:
        lines.append("transfer hops:")
        for h in hops:
            mbs = (
                f", {h['bytes'] / max(h['duration_s'], 1e-9) / (1 << 20):.1f}"
                " MB/s"
                if h["bytes"]
                else ""
            )
            lines.append(
                f"  {h['stage']}: {h['src']} -> {h['dst']}  "
                f"{h['duration_s'] * 1e3:.1f}ms, {h['bytes']} bytes{mbs}"
            )
    return "\n".join(lines)


def transfer_hops(spans: list[Span]) -> list[dict]:
    """The trace's KV transfer hops (send/recv spans with their link
    endpoints), start-ordered — the per-trace view the TransferLedger's
    per-link bandwidth estimates must be consistent with."""
    hops = []
    for s in sorted(spans, key=lambda x: x.start):
        if s.stage not in ("kv_transfer_send", "kv_transfer_recv"):
            continue
        hops.append(
            {
                "stage": s.stage,
                "src": str(s.attrs.get("src", s.attrs.get("instance", "?"))),
                "dst": str(s.attrs.get("dst", "?")),
                "bytes": int(s.attrs.get("bytes", 0) or 0),
                "duration_s": s.duration_s,
            }
        )
    return hops


def list_traces(spans: list[Span]) -> list[tuple[str, int, float, str]]:
    """(trace_id, span count, duration_s, root stage) per trace, by
    start time — the ``llmctl trace`` no-argument listing."""
    by_trace: dict[str, list[Span]] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    rows = []
    for tid, group in by_trace.items():
        t0 = min(s.start for s in group)
        t1 = max(s.end for s in group)
        root = min(group, key=lambda s: s.start)
        rows.append((tid, len(group), t1 - t0, root.stage, t0))
    rows.sort(key=lambda r: r[-1])
    return [(tid, n, dur, stage) for tid, n, dur, stage, _t0 in rows]
