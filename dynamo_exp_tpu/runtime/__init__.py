"""Distributed runtime: the accelerator-agnostic serving fabric."""

from .annotated import Annotated
from .client import Client, EngineError
from .component import (
    DRAIN_PREFIX,
    Component,
    DistributedRuntime,
    Endpoint,
    Namespace,
    ServedInstance,
    annotated_stream,
)
from .config import RuntimeConfig
from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    DeadlineExceededError,
    LambdaEngine,
    ResponseStream,
)
from .health import BreakerState, CircuitBreaker, HealthTracker, is_draining
from .journal import ReplayJournal
from .logging import configure_logging
from .pipeline import (
    Context,
    MapOperator,
    Operator,
    PipelineNode,
    PipelineOperator,
    SegmentSink,
    SegmentSource,
    ServiceBackend,
    ServiceFrontend,
    build_pipeline,
    build_segment,
)
from .pool import Pool, PoolItem
from .reclaim import (
    MigrationSink,
    ReclaimController,
    SequenceSnapshot,
    SurvivorInfo,
    install_sigterm_reclaim,
    migration_lease_ttl_s,
    plan_triage,
)
from .push_router import (
    NoHealthyInstancesError,
    NoInstancesError,
    PushRouter,
    RecoveryExhaustedError,
    RouterMode,
)
from .runtime import CancellationToken, Runtime, Worker
from .transports.base import EndpointAddress, InstanceInfo, Lease

__all__ = [
    "Annotated",
    "AsyncEngine",
    "AsyncEngineContext",
    "BreakerState",
    "CancellationToken",
    "CircuitBreaker",
    "Client",
    "Component",
    "Context",
    "DRAIN_PREFIX",
    "DeadlineExceededError",
    "DistributedRuntime",
    "Endpoint",
    "EndpointAddress",
    "EngineError",
    "HealthTracker",
    "InstanceInfo",
    "LambdaEngine",
    "Lease",
    "MapOperator",
    "MigrationSink",
    "Namespace",
    "NoHealthyInstancesError",
    "NoInstancesError",
    "Operator",
    "PipelineNode",
    "PipelineOperator",
    "Pool",
    "PoolItem",
    "PushRouter",
    "ReclaimController",
    "RecoveryExhaustedError",
    "ReplayJournal",
    "ResponseStream",
    "RouterMode",
    "Runtime",
    "RuntimeConfig",
    "SegmentSink",
    "SegmentSource",
    "SequenceSnapshot",
    "ServedInstance",
    "SurvivorInfo",
    "ServiceBackend",
    "ServiceFrontend",
    "Worker",
    "annotated_stream",
    "build_pipeline",
    "build_segment",
    "configure_logging",
    "install_sigterm_reclaim",
    "is_draining",
    "migration_lease_ttl_s",
    "plan_triage",
]
