"""TPU chip allocation for multi-service hosts.

Reference parity: ``deploy/dynamo/sdk/cli/allocator.py:53-120``
(``ResourceAllocator.assign_gpus`` → ``CUDA_VISIBLE_DEVICES`` per
watcher). TPU equivalent: disjoint chip sets per service process via
``TPU_VISIBLE_CHIPS`` (libtpu) — also exported as
``TPU_VISIBLE_DEVICES`` for older runtimes. A service asks with
``resources={"tpu": n}``; services with no tpu request get no chips and
the TPU runtime is told to stay off (``JAX_PLATFORMS=cpu``), so
frontends/routers never grab the accelerator.
"""

from __future__ import annotations

import os


class AllocationError(RuntimeError):
    pass


class TPUAllocator:
    """Free-set allocator: chips released by a scaled-down worker are
    handed to the next scale-up (the planner adds/removes workers at
    runtime, so a bump pointer would leak the budget)."""

    def __init__(self, total_chips: int | None = None):
        if total_chips is None:
            total_chips = int(os.environ.get("DYN_TPU_CHIPS", "4"))
        self.total_chips = total_chips
        self._free = set(range(total_chips))

    @property
    def available(self) -> int:
        return len(self._free)

    def assign(self, service_name: str, chips: int) -> dict[str, str]:
        """Env vars for one worker process of ``service_name``."""
        if chips <= 0:
            # Host-side service: keep JAX off the TPU entirely.
            return {"JAX_PLATFORMS": "cpu"}
        if chips > len(self._free):
            raise AllocationError(
                f"{service_name} wants {chips} TPU chips but only "
                f"{len(self._free)} of {self.total_chips} remain"
            )
        ids = sorted(self._free)[:chips]
        self._free -= set(ids)
        joined = ",".join(str(i) for i in ids)
        return {"TPU_VISIBLE_CHIPS": joined, "TPU_VISIBLE_DEVICES": joined}

    def release(self, env: dict[str, str]) -> None:
        """Return a worker's chips (from its assign() env) to the pool."""
        ids = env.get("TPU_VISIBLE_CHIPS", "")
        if ids:
            self._free |= {int(i) for i in ids.split(",")}
