"""SentencePiece ``tokenizer.model`` backend without the sentencepiece lib.

Capability parity with the reference's SentencePiece tokenizer backend
(``/root/reference/lib/llm/src/tokenizers/sp.rs:1-109``): load a model
directory that ships only ``tokenizer.model`` (no tokenizer.json) and
serve it. The reference links the sentencepiece C++ library; here the
``.model`` file — a protobuf ``ModelProto`` — is parsed directly with a
minimal wire-format reader (varint + length-delimited fields are all we
need), and the pieces feed the exact Unigram construction that
``gguf_tokenizer.py`` uses, since SentencePiece *is* the unigram model.

ModelProto layout (sentencepiece.proto):
  field 1: repeated SentencePiece { 1: piece (string),
                                    2: score (float),
                                    3: type  (enum) }
  field 2: TrainerSpec  { 40: unk_id, 41: bos_id, 42: eos_id, ... }
"""

from __future__ import annotations

import struct

# SentencePiece piece types.
SP_NORMAL = 1
SP_UNKNOWN = 2
SP_CONTROL = 3
SP_USER_DEFINED = 4
SP_UNUSED = 5
SP_BYTE = 6

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_LEN = 2
_WIRE_I32 = 5


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Iterate (field_number, wire_type, value) over one message."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            val, pos = _read_varint(buf, pos)
        elif wire == _WIRE_I64:
            val = buf[pos : pos + 8]
            pos += 8
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos : pos + ln]
            pos += ln
        elif wire == _WIRE_I32:
            val = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wire}")
        yield field, wire, val


def parse_sentencepiece_model(path: str):
    """Return (pieces, special_ids) from a ``tokenizer.model`` file.

    ``pieces`` is ``[(piece, score, type), ...]`` in id order;
    ``special_ids`` maps {"unk"|"bos"|"eos"|"pad": id} for ids the
    TrainerSpec pins (-1 entries are omitted).
    """
    with open(path, "rb") as f:
        buf = f.read()
    pieces: list[tuple[str, float, int]] = []
    special_ids: dict[str, int] = {}
    for field, wire, val in _fields(buf):
        if field == 1 and wire == _WIRE_LEN:  # repeated SentencePiece
            piece, score, ptype = "", 0.0, SP_NORMAL
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == _WIRE_LEN:
                    piece = v2.decode("utf-8")
                elif f2 == 2 and w2 == _WIRE_I32:
                    score = struct.unpack("<f", v2)[0]
                elif f2 == 3 and w2 == _WIRE_VARINT:
                    ptype = v2
            pieces.append((piece, score, ptype))
        elif field == 2 and wire == _WIRE_LEN:  # TrainerSpec
            ids = {40: "unk", 41: "bos", 42: "eos", 43: "pad"}
            for f2, w2, v2 in _fields(val):
                if f2 in ids and w2 == _WIRE_VARINT:
                    # negative ids are varint-encoded as 2^64-|x|; treat
                    # anything that large as "disabled".
                    if v2 < 1 << 31:
                        special_ids[ids[f2]] = v2
    if not pieces:
        raise ValueError(f"{path} contains no sentencepiece pieces")
    return pieces, special_ids


def tokenizer_backend_from_sp(path: str, add_bos: bool = True):
    """Build a ``tokenizers.Tokenizer`` (Unigram) from a ``.model`` file."""
    from tokenizers import AddedToken

    from .gguf_tokenizer import _build_unigram

    pieces, special_ids = parse_sentencepiece_model(path)
    tokens = [p for p, _, _ in pieces]
    scores = [s for _, s, _ in pieces]
    unk_id = special_ids.get("unk")
    if unk_id is None:
        unk = [i for i, (_, _, t) in enumerate(pieces) if t == SP_UNKNOWN]
        unk_id = unk[0] if unk else 0
    tok = _build_unigram(tokens, scores, unk_id)

    control = [
        AddedToken(p, special=True)
        for p, _, t in pieces
        if t in (SP_CONTROL, SP_UNKNOWN)
    ]
    if control:
        tok.add_special_tokens(control)

    bos_id = special_ids.get("bos")
    if add_bos and bos_id is not None:
        from tokenizers import processors

        bos = tokens[bos_id]
        tok.post_processor = processors.TemplateProcessing(
            single=f"{bos} $A",
            pair=f"{bos} $A {bos} $B",
            special_tokens=[(bos, bos_id)],
        )
    return tok


