"""llmctl CLI, standalone KV-router service, and request template tests.

Reference capability anchors: ``launch/llmctl/src/main.rs:101-454``,
``components/router/src/main.rs:33-60``, ``lib/llm/src/request_template.rs``.
"""

import asyncio
import json

from dynamo_exp_tpu import llmctl
from dynamo_exp_tpu.local_model import MODELS_PREFIX, ModelEntry
from dynamo_exp_tpu.protocols.request_template import RequestTemplate
from dynamo_exp_tpu.runtime.component import DistributedRuntime
from dynamo_exp_tpu.runtime.config import RuntimeConfig
from dynamo_exp_tpu.runtime.transports.coordinator import CoordinatorServer


async def _with_coordinator():
    server = CoordinatorServer()
    await server.start()
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=server.address)
    )
    return server, drt


# ------------------------------------------------------------------ llmctl
async def test_llmctl_add_list_remove(capsys):
    server, drt = await _with_coordinator()
    try:
        parser = llmctl.build_parser()
        add = parser.parse_args(
            ["--coordinator", server.address, "http", "add",
             "chat-model", "foo/v1", "TpuWorker.generate"]
        )
        assert await llmctl.add_model(drt, add) == 0

        entries = await drt.discovery.kv_get_prefix(MODELS_PREFIX)
        assert len(entries) == 1
        e = ModelEntry.from_bytes(next(iter(entries.values())))
        assert e.name == "foo/v1"
        assert e.endpoint == "dyn://dynamo.TpuWorker.generate"
        assert e.model_type == "chat"

        lst = parser.parse_args(
            ["--coordinator", server.address, "http", "list", "--json"]
        )
        assert await llmctl.list_models(drt, lst) == 0
        out = capsys.readouterr().out.strip().splitlines()[-1]
        rows = json.loads(out)
        assert rows == [
            {"name": "foo/v1", "type": "chat",
             "endpoint": "dyn://dynamo.TpuWorker.generate",
             "owner": "llmctl-chat"}
        ]

        # A completion registration under the SAME name coexists with
        # the chat one, and remove is type-scoped.
        add2 = parser.parse_args(
            ["--coordinator", server.address, "http", "add",
             "completion-model", "foo/v1", "TpuWorker.generate"]
        )
        assert await llmctl.add_model(drt, add2) == 0
        assert len(await drt.discovery.kv_get_prefix(MODELS_PREFIX)) == 2

        rm_comp = parser.parse_args(
            ["--coordinator", server.address, "http", "remove",
             "completion-model", "foo/v1"]
        )
        assert await llmctl.remove_model(drt, rm_comp) == 0
        left = await drt.discovery.kv_get_prefix(MODELS_PREFIX)
        assert len(left) == 1
        assert ModelEntry.from_bytes(next(iter(left.values()))).model_type == "chat"

        rm = parser.parse_args(
            ["--coordinator", server.address, "http", "remove",
             "model", "foo/v1"]
        )
        assert await llmctl.remove_model(drt, rm) == 0
        assert not await drt.discovery.kv_get_prefix(MODELS_PREFIX)
        # Removing again reports failure.
        assert await llmctl.remove_model(drt, rm) == 1
    finally:
        await drt.close()
        await server.close()


def test_llmctl_endpoint_qualification():
    assert llmctl._qualify("a.b", "ns") == "dyn://ns.a.b"
    assert llmctl._qualify("x.a.b", "ns") == "dyn://x.a.b"
    assert llmctl._qualify("dyn://x.a.b", "ns") == "dyn://x.a.b"


# ----------------------------------------------------------- router service
async def test_standalone_router_service_routes_by_overlap():
    """The router service watches a worker component's KV events and
    answers scheduling queries over the request plane."""
    from dynamo_exp_tpu.components.router import RouterService
    from dynamo_exp_tpu.kv_router.protocols import (
        KvCacheEventData,
        RouterEvent,
        kv_events_subject,
    )
    from dynamo_exp_tpu.tokens import compute_block_hashes_for_seq, chain_hash

    server, drt = await _with_coordinator()
    svc = None
    worker = None
    try:
        # A live worker with load stats: the scheduler only considers
        # workers whose metrics it can scrape.
        async def noop(request, context=None):
            yield {"data": {}}

        stats = {
            "request_active_slots": 1, "request_total_slots": 8,
            "kv_active_blocks": 4, "kv_total_blocks": 64,
            "num_requests_waiting": 0, "gpu_cache_usage_perc": 0.05,
            "gpu_prefix_cache_hit_rate": 0.0,
        }
        workers_comp = drt.namespace("ns").component("Workers")
        worker = await workers_comp.endpoint("generate").serve_endpoint(
            noop, stats_handler=lambda: stats
        )
        wid = worker.instance_id

        svc = RouterService(drt, "ns", "Workers", block_size=4)
        await svc.start()

        # The worker announces pages for the prefix of a known prompt.
        prompt = list(range(16))
        hashes = compute_block_hashes_for_seq(prompt, 4)
        await drt.event_plane.publish(
            kv_events_subject(workers_comp.path),
            RouterEvent(
                worker_id=wid,
                data=KvCacheEventData(
                    kind="stored", block_hashes=hashes[:2], parent_hash=None
                ),
            ).to_dict(),
        )
        await asyncio.sleep(0.3)  # indexer consume + metrics scrape

        ep = drt.namespace("ns").component("kv_aware_router").endpoint(
            "generate"
        )
        client = await ep.client()
        await client.wait_for_instances(1, timeout=10)
        stream = await client.generate_to(
            client.instances[0], {"token_ids": prompt}
        )
        replies = [a.data async for a in stream if a.data is not None]
        assert replies and replies[0]["worker_id"] == wid
        assert replies[0]["overlap_blocks"] == 2
    finally:
        if svc is not None:
            await svc.stop()
        if worker is not None:
            await worker.close()
        await drt.close()
        await server.close()


# --------------------------------------------------------- request template
def test_request_template_applies_defaults(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(json.dumps(
        {"model": "foo", "temperature": 0.6, "max_completion_tokens": 42}
    ))
    t = RequestTemplate.load(str(p))
    req = t.apply({"messages": []})
    assert req["model"] == "foo"
    assert req["temperature"] == 0.6
    assert req["max_completion_tokens"] == 42
    # Explicit values win.
    req = t.apply({"model": "bar", "temperature": 0.0, "max_tokens": 5})
    assert req["model"] == "bar"
    assert req["temperature"] == 0.0
    assert "max_completion_tokens" not in req


async def test_request_template_through_http_service():
    from aiohttp import ClientSession

    from dynamo_exp_tpu.engines.echo import EchoEngineFull
    from dynamo_exp_tpu.http import HttpService

    t = RequestTemplate(model="echo", max_completion_tokens=3)
    svc = HttpService(host="127.0.0.1", port=0, request_template=t)
    svc.manager.add_completion_model("echo", EchoEngineFull())
    port = await svc.start()
    try:
        async with ClientSession() as sess:
            # No model in the body: the template routes it.
            async with sess.post(
                f"http://127.0.0.1:{port}/v1/completions",
                json={"prompt": "a b c d e"},
            ) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        assert data["model"] == "echo"
    finally:
        await svc.stop()
