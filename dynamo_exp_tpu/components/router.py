"""Standalone KV-aware router service.

Capability parity with ``/root/reference/components/router/src/main.rs``
(:33-60): the KV router as its own discoverable component — it watches a
worker component's KV events + load metrics and serves
``RouterRequest{token_ids} → RouterResponse{worker_id, overlap_blocks}``
on a ``generate`` endpoint, so any ingress (not just the embedded
in-process router) can ask "which worker for these tokens?".

    python -m dynamo_exp_tpu.components.router \
        --coordinator HOST:PORT --namespace dynamo \
        --workers TpuWorker --block-size 16 \
        [--component kv_aware_router]
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

logger = logging.getLogger(__name__)


class RouterService:
    """Owns a KvRouter watching ``worker_component`` and serves it on
    ``router_component``'s ``generate`` endpoint."""

    def __init__(
        self,
        drt,
        namespace: str,
        worker_component: str,
        block_size: int,
        router_component: str = "kv_aware_router",
    ):
        from ..kv_router.router import KvRouter

        self.drt = drt
        self.router = KvRouter(
            drt.namespace(namespace).component(worker_component),
            block_size=block_size,
        )
        self.endpoint = (
            drt.namespace(namespace)
            .component(router_component)
            .endpoint("generate")
        )
        self._served = None

    async def start(self) -> int:
        from ..runtime.component import annotated_stream

        await self.router.start()

        async def handler(request: dict, context=None):
            async for frame in annotated_stream(self.router, request, context):
                yield frame

        self._served = await self.endpoint.serve_endpoint(handler)
        logger.info(
            "kv router serving %s (watching %s)",
            self.endpoint.path,
            self.router.component.path,
        )
        return self._served.instance_id

    async def stop(self) -> None:
        if self._served is not None:
            await self._served.close()
            self._served = None
        await self.router.stop()


def main() -> None:  # pragma: no cover - CLI entry
    import argparse

    from ..runtime.component import DistributedRuntime
    from ..runtime.config import RuntimeConfig

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--coordinator", required=True)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--workers", required=True,
                   help="worker component whose KV events/metrics to watch")
    p.add_argument("--block-size", type=int, required=True)
    p.add_argument("--component", default="kv_aware_router")
    args = p.parse_args()

    async def run():
        drt = DistributedRuntime(
            config=RuntimeConfig(coordinator_endpoint=args.coordinator)
        )
        svc = RouterService(
            drt, args.namespace, args.workers, args.block_size, args.component
        )
        iid = await svc.start()
        print(f"kv router instance {iid}", flush=True)
        with contextlib.suppress(asyncio.CancelledError):
            await asyncio.Event().wait()
        await svc.stop()
        await drt.close()

    logging.basicConfig(level="INFO")
    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
