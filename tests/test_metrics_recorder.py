"""Metrics exporter, mock worker, and recorder tests.

Reference capability anchors: ``components/metrics`` (Prometheus
collector + mock worker) and ``lib/llm/src/recorder.rs`` /
``kv_router/recorder.rs`` (JSONL record + replay).
"""

import asyncio
import os

from dynamo_exp_tpu.components.metrics import MetricsService
from dynamo_exp_tpu.components.mock_worker import MockWorker
from dynamo_exp_tpu.kv_router.indexer import KvIndexer
from dynamo_exp_tpu.kv_router.protocols import (
    KV_HIT_RATE_SUBJECT,
    KVHitRateEvent,
    KvCacheEventData,
    RouterEvent,
    kv_events_subject,
)
from dynamo_exp_tpu.recorder import KvRecorder, Recorder
from dynamo_exp_tpu.runtime.component import DistributedRuntime
from dynamo_exp_tpu.runtime.transports.inproc import (
    InProcDiscovery,
    InProcRequestPlane,
)


def make_drt() -> DistributedRuntime:
    return DistributedRuntime(
        discovery=InProcDiscovery(), request_plane=InProcRequestPlane()
    )


# ----------------------------------------------------------------- exporter
async def test_metrics_exporter_scrapes_mock_worker():
    drt = make_drt()
    comp = drt.namespace("m").component("worker")
    worker = MockWorker(comp)
    await worker.start()
    svc = MetricsService(comp, host="127.0.0.1", port=0, scrape_interval_s=0.05)
    try:
        port = await svc.start()
        # A routing decision event lands in the counters.
        await drt.event_plane.publish(
            KV_HIT_RATE_SUBJECT,
            KVHitRateEvent(worker_id=1, isl_blocks=10, overlap_blocks=4).to_dict(),
        )
        await asyncio.sleep(0.3)  # a few scrape cycles
        text = svc.render().decode()
        assert "llm_kv_request_total_slots" in text
        assert 'worker_id="' in text
        assert "llm_kv_hit_events_total 1.0" in text
        assert "llm_kv_hit_overlap_blocks_total 4.0" in text

        # And it serves over HTTP.
        import aiohttp

        async with aiohttp.ClientSession() as http:
            r = await http.get(f"http://127.0.0.1:{port}/metrics")
            assert r.status == 200
            assert "llm_kv_request_total_slots" in await r.text()
    finally:
        await svc.stop()
        await worker.stop()


async def test_metrics_exporter_drops_departed_workers():
    drt = make_drt()
    comp = drt.namespace("m2").component("worker")
    worker = MockWorker(comp)
    await worker.start()
    svc = MetricsService(comp, port=0, scrape_interval_s=0.05)
    try:
        await svc.start()
        await asyncio.sleep(0.2)
        assert 'worker_id="' in svc.render().decode()
        await worker.stop()  # instance deregisters
        await asyncio.sleep(0.3)
        # Gauge series for the departed worker are removed.
        text = svc.render().decode()
        assert 'llm_kv_request_total_slots{worker_id="' not in text
    finally:
        await svc.stop()


# ----------------------------------------------------------------- recorder
def test_recorder_roundtrip_and_rotation(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = Recorder(path, max_bytes=200, max_files=2)
    for i in range(20):
        rec.record({"i": i})
    rec.close()
    assert os.path.exists(path + ".1")  # rotated at least once
    assert not os.path.exists(path + ".3")  # capped generations
    # Replay of the live file yields the newest events in order.
    events = [e for _ts, e in Recorder.replay(path)]
    assert events == sorted(events, key=lambda e: e["i"])


def test_recorder_skips_corrupt_lines(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = Recorder(path)
    rec.record({"ok": 1})
    rec.close()
    with open(path, "a") as f:
        f.write("{torn-write\n")
    assert [e for _, e in Recorder.replay(path)] == [{"ok": 1}]


async def test_kv_recorder_record_and_replay_into_indexer(tmp_path):
    drt = make_drt()
    subject = kv_events_subject("x/components/w")
    path = str(tmp_path / "kv.jsonl")
    kvrec = KvRecorder(Recorder(path))
    await kvrec.start(drt.event_plane, subject)

    idx_live = KvIndexer(block_size=4)
    hashes = idx_live.block_hashes([1, 2, 3, 4, 5, 6, 7, 8])
    parent = None
    for h in hashes:
        ev = RouterEvent(
            worker_id=7,
            data=KvCacheEventData(kind="stored", block_hashes=[h], parent_hash=parent),
        )
        await drt.event_plane.publish(subject, ev.to_dict())
        parent = h
    for _ in range(100):
        if kvrec.recorder.count >= len(hashes):
            break
        await asyncio.sleep(0.01)
    await kvrec.stop()

    # Rebuild an index purely from the recording.
    idx = KvIndexer(block_size=4)
    n = KvRecorder.replay_into(path, idx)
    assert n == len(hashes)
    scores = idx.find_matches_for_request([1, 2, 3, 4, 5, 6, 7, 8])
    assert scores.scores.get(7) == len(hashes)
