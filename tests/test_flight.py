"""Engine flight recorder + watchdog (docs/observability.md "Engine
flight recorder & watchdog").

Acceptance path: a seeded, chaos-injected engine stall (two sequences
whose KV growth drains a tiny pool with preemption disabled — the
permanent-wedge shape PR 5's fixes made otherwise unreachable) must
produce EXACTLY ONE flight dump whose event sequence is identical
across same-seed runs, and ``llmctl flight`` must render it into a
per-slot timeline naming the stalled slots. Plus: no false positive
under a slow-but-progressing workload, watchdog/ring units, and the
dump render.

Determinism protocol (the PR-3/PR-5 gotcha applies: admission of
concurrent submissions is an OS race): the stall phase pre-queues its
sequences into the submit queue while the engine is stopped and clears
the ring, so the loop drains them in one deterministic pass; only the
per-event wall timestamp ``t`` differs between runs and is popped
before comparison.
"""

import asyncio
import json
import os
import time

import pytest

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.engine.scheduler import Sequence
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput
from dynamo_exp_tpu.telemetry.flight import (
    FlightRecorder,
    Watchdog,
    load_dumps,
    render_flight,
)

pytestmark = pytest.mark.chaos

PS = 8
SEEDS = tuple(
    int(s) for s in os.environ.get("CHAOS_SEEDS", "7,21,1337").split(",")
)[:1]


# ------------------------------------------------------------------- units
def test_ring_bounds_and_order():
    fr = FlightRecorder(capacity=16)
    for i in range(20):
        fr.record("e", i=i)
    evs = fr.snapshot()
    assert len(evs) == 16
    assert [e["i"] for e in evs] == list(range(4, 20))
    assert [e["seq"] for e in evs] == list(range(4, 20))
    fr.clear()
    assert fr.snapshot() == [] and fr.seq == 0


def test_dump_and_load_roundtrip(tmp_path):
    fr = FlightRecorder()
    fr.record("admit", req="r1", slot=0)
    fr.record("stall_start", req="r1", slot=0)
    path = str(tmp_path / "f.jsonl")
    fr.dump(path, "watchdog", snapshot={"waiting": 2, "slots": []})
    fr.dump(path, "sigusr1")  # second block appends
    blocks = load_dumps(path)
    assert len(blocks) == 2
    assert blocks[0]["header"]["reason"] == "watchdog"
    assert [e["kind"] for e in blocks[0]["events"]] == [
        "admit", "stall_start",
    ]
    assert blocks[0]["snapshot"]["waiting"] == 2
    assert blocks[1]["snapshot"] is None


def test_render_names_stalled_slot():
    block = {
        "header": {"reason": "watchdog", "t": 10.0},
        "events": [
            {"seq": 0, "t": 10.0, "kind": "admit", "req": "req-a", "slot": 1},
            {"seq": 1, "t": 10.5, "kind": "stall_start", "req": "req-a",
             "slot": 1},
            {"seq": 2, "t": 10.2, "kind": "dispatch", "dispatch": "decode",
             "rows": 1},
        ],
        "snapshot": {
            "t": 11.0,
            "waiting": 1,
            "slots": [
                {"slot": 1, "req": "req-a", "state": "active",
                 "generated": 5, "pages": 4, "stalled": True},
            ],
        },
    }
    out = render_flight(block)
    assert "reason=watchdog" in out
    assert "slot 1" in out and "req-a" in out
    assert "STALLED" in out
    assert "stall_start" in out and "dispatch=decode" in out
    assert "waiting=1" in out


def test_watchdog_fires_once_per_stall_episode():
    progress = {"n": 0}
    busy = {"v": True}
    dumps = []
    wd = Watchdog(
        stall_s=0.1,
        progress=lambda: progress["n"],
        has_work=lambda: busy["v"],
        dump_fn=dumps.append,
        poll_s=0.02,
    )
    wd.start()
    try:
        # Progressing: no dump.
        for _ in range(8):
            progress["n"] += 1
            time.sleep(0.03)
        assert dumps == []
        # Frozen with work queued: exactly one dump per episode.
        time.sleep(0.3)
        assert dumps == ["watchdog"]
        time.sleep(0.2)
        assert dumps == ["watchdog"]
        # Progress resumes, then freezes again: a second episode.
        progress["n"] += 1
        time.sleep(0.05)
        time.sleep(0.3)
        assert dumps == ["watchdog", "watchdog"]
    finally:
        wd.stop()


def test_watchdog_idle_never_dumps():
    dumps = []
    wd = Watchdog(
        stall_s=0.05,
        progress=lambda: 0,
        has_work=lambda: False,  # frozen but idle: nothing is wedged
        dump_fn=dumps.append,
        poll_s=0.01,
    )
    wd.start()
    time.sleep(0.2)
    wd.stop()
    assert dumps == []


# ------------------------------------------------------- engine stall chaos
def _stall_cfg(dump_path: str, **over) -> EngineConfig:
    base = dict(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=8,  # 64 tokens total: two growing rows drain it
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
        decode_window=4,
        preempt_stall_grace_s=-1.0,  # chaos: preemption disabled -> wedge
        watchdog_stall_s=-1.0,  # enabled only after warmup
        flight_dump_path=dump_path,
    )
    return EngineConfig(**(base | over))


def _stall_seq(rid: str, prompt: list[int], max_tokens: int) -> Sequence:
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = True
    return Sequence(
        request_id=rid,
        prompt=list(prompt),
        stop=b,
        emit=lambda *a, **k: None,
        is_cancelled=lambda: False,
        submitted_at=time.time(),
        sample_seed=7,
    )


async def _warmup(engine: TPUEngine, seed: int) -> None:
    """Compile every variant the stall phase touches (prefill rows 1+2,
    decode rows 1+2 at the small page buckets) so no multi-second
    compile pauses the loop once the watchdog is armed."""
    import numpy as np

    rs = np.random.RandomState(seed)

    async def one(prompt, toks):
        b = BackendInput(token_ids=[int(t) for t in prompt])
        b.stop_conditions.max_tokens = toks
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        async for _ in stream:
            pass

    prompts = [rs.randint(10, TINY.vocab_size - 10, size=16) for _ in range(2)]
    await asyncio.gather(*[one(p, 8) for p in prompts])  # rows-2 shapes
    await one(rs.randint(10, TINY.vocab_size - 10, size=16), 8)  # rows-1


def _run_stall_once(tmp_path, seed: int, tag: str) -> tuple[list, dict, str]:
    """One full seeded stall episode; returns (event lines sans wall
    time, snapshot, dump path)."""
    import numpy as np

    dump_path = str(tmp_path / f"flight_{tag}.jsonl")
    engine = TPUEngine(
        _stall_cfg(dump_path), mesh=single_device_mesh(), seed=0
    )
    engine.start()
    asyncio.run(_warmup(engine, seed))
    # Re-arm: pre-queue the stall workload while the loop is down, so
    # the first iteration drains and admits it deterministically.
    engine.stop()
    engine.flight.clear()
    engine.cfg.watchdog_stall_s = 1.5
    rs = np.random.RandomState(seed)
    for rid in ("req-a", "req-b"):
        prompt = [int(t) for t in rs.randint(10, TINY.vocab_size - 10, size=16)]
        engine._submit_q.put(_stall_seq(rid, prompt, max_tokens=100))
    engine.start()
    try:
        deadline = time.monotonic() + 30
        while not os.path.exists(dump_path) and time.monotonic() < deadline:
            time.sleep(0.05)
        # Exactly one dump: give a second stall period a chance to
        # (wrongly) fire again, then read the file once.
        time.sleep(2.0)
        assert os.path.exists(dump_path), "watchdog never dumped"
    finally:
        engine.stop()
    blocks = load_dumps(dump_path)
    assert len(blocks) == 1, f"expected exactly one dump, got {len(blocks)}"
    events = []
    for ev in blocks[0]["events"]:
        d = dict(ev)
        d.pop("t", None)  # the only cross-run-variable field
        events.append(d)
    return events, blocks[0]["snapshot"], dump_path


@pytest.mark.nightly
@pytest.mark.parametrize("seed", SEEDS)
def test_injected_stall_dumps_once_and_is_seed_deterministic(
    tmp_path, seed, capsys
):
    events1, snap1, dump_path = _run_stall_once(tmp_path, seed, "run1")
    events2, snap2, _ = _run_stall_once(tmp_path, seed, "run2")

    # The wedge really is the KV stall: both rows hard-stalled, work
    # queued, nothing moving.
    stalled = [s for s in snap1["slots"] if s["stalled"]]
    assert len(stalled) == 2
    kinds = [e["kind"] for e in events1]
    assert "admit" in kinds and "stall_start" in kinds
    assert any(e["kind"] == "dispatch" for e in events1)

    # Bit-identical event sequence across same-seed runs (wall time
    # popped; everything else — order, kinds, payloads, seq — equal).
    assert json.dumps(events1) == json.dumps(events2)
    # Snapshot agrees on the deterministic scheduler state too.
    assert snap1["slots"] == snap2["slots"]
    assert snap1["waiting"] == snap2["waiting"]

    # llmctl flight renders a per-slot timeline naming the stalled slot.
    from dynamo_exp_tpu.llmctl import main as llmctl_main

    assert llmctl_main(["flight", dump_path]) == 0
    out = capsys.readouterr().out
    assert "reason=watchdog" in out
    assert "req-a" in out and "req-b" in out
    assert "STALLED" in out
    for s in stalled:
        assert f"slot {s['slot']}" in out


@pytest.mark.nightly
def test_no_false_positive_under_slow_but_progressing_workload(tmp_path):
    """A workload that keeps making progress — however slowly — must
    never trigger the watchdog, even with a tight stall threshold
    (warmup happens before the watchdog is armed, so compiles can't
    masquerade as stalls)."""
    dump_path = str(tmp_path / "flight_fp.jsonl")
    engine = TPUEngine(
        _stall_cfg(dump_path, num_pages=64, preempt_stall_grace_s=0.5),
        mesh=single_device_mesh(),
        seed=0,
    )
    engine.start()
    asyncio.run(_warmup(engine, 3))
    engine.stop()
    engine.cfg.watchdog_stall_s = 0.6
    engine.start()
    try:

        async def trickle():
            import numpy as np

            rs = np.random.RandomState(1)
            for _ in range(3):
                b = BackendInput(
                    token_ids=[
                        int(t)
                        for t in rs.randint(10, TINY.vocab_size - 10, size=16)
                    ]
                )
                b.stop_conditions.max_tokens = 48
                b.stop_conditions.ignore_eos = True
                stream = await engine.generate(b.to_dict())
                async for _ in stream:
                    pass
                await asyncio.sleep(0.15)

        asyncio.run(trickle())
        time.sleep(0.8)  # one more full stall window while idle
    finally:
        engine.stop()
    assert not os.path.exists(dump_path), "watchdog false positive"


def test_llmctl_flight_list_and_errors(tmp_path, capsys):
    from dynamo_exp_tpu.llmctl import main as llmctl_main

    missing = str(tmp_path / "nope.jsonl")
    assert llmctl_main(["flight", missing]) == 2
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert llmctl_main(["flight", str(empty)]) == 1

    fr = FlightRecorder()
    fr.record("admit", req="r", slot=0)
    path = str(tmp_path / "ok.jsonl")
    fr.dump(path, "sigusr1")
    fr.dump(path, "crash")
    assert llmctl_main(["flight", path, "--list"]) == 0
    out = capsys.readouterr().out
    assert "reason=sigusr1" in out and "reason=crash" in out
    assert llmctl_main(["flight", path, "--index", "5"]) == 1
