"""Disaggregation config: stored in the control-plane KV, watched live.

Reference parity: ``DisaggRouterConf`` read from etcd key
``public/components/disagg_router/models/chat/{model}`` with a live
watch feeding runtime reconfiguration
(``/root/reference/lib/llm/src/disagg_router.rs:24-262``) and the
decision logic of ``examples/llm/components/disagg_router.py:1-66``.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
from dataclasses import asdict, dataclass

from ..runtime.transports.base import Discovery

logger = logging.getLogger(__name__)


def disagg_config_key(model: str) -> str:
    return f"public/disagg_router/models/{model}"


@dataclass
class DisaggConfig:
    """Tunables for the remote-prefill decision.

    ``max_local_prefill_length``: prompts with more uncached tokens than
    this go to a prefill worker. ``max_prefill_queue_size``: but not if
    the queue is already this deep (prefill workers saturated — local
    prefill beats queueing).
    """

    max_local_prefill_length: int = 1024
    max_prefill_queue_size: int = 2

    def prefill_remote(self, prefill_length: int, queue_size: int) -> bool:
        return (
            prefill_length > self.max_local_prefill_length
            and queue_size < self.max_prefill_queue_size
        )

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "DisaggConfig":
        return cls(**json.loads(raw))


class DisaggConfigWatcher:
    """Live view of a model's DisaggConfig from the control-plane KV.

    ``current()`` is synchronous and lock-free (read by the request hot
    path); a background watch task applies updates as they land.
    """

    def __init__(
        self,
        discovery: Discovery,
        model: str,
        default: DisaggConfig | None = None,
    ):
        self._discovery = discovery
        self._key = disagg_config_key(model)
        self._config = default or DisaggConfig()
        self._task: asyncio.Task | None = None

    def current(self) -> DisaggConfig:
        return self._config

    async def start(self) -> None:
        """Load the initial value, then follow updates."""
        raw = await self._discovery.kv_get(self._key)
        if raw:
            self._config = DisaggConfig.from_bytes(raw)
        self._task = asyncio.ensure_future(self._follow())

    async def publish(self, config: DisaggConfig) -> None:
        """Write a new config for every watcher of this model."""
        await self._discovery.kv_put(self._key, config.to_bytes())

    async def _follow(self) -> None:
        # The watch raises (e.g. ConnectionError) when the coordinator
        # connection drops; without the retry loop the live-reconfig
        # feature would silently freeze at its last value forever.
        while True:
            try:
                async for snapshot in self._discovery.kv_watch_prefix(self._key):
                    raw = snapshot.get(self._key)
                    if raw:
                        try:
                            self._config = DisaggConfig.from_bytes(raw)
                            logger.info("disagg config updated: %s", self._config)
                        except (ValueError, TypeError, KeyError):
                            logger.warning("ignoring malformed disagg config")
            except asyncio.CancelledError:
                return
            except Exception as exc:
                logger.warning("disagg config watch lost (%s); retrying", exc)
                await asyncio.sleep(1.0)

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
