"""Shared fixtures for the test suite."""

import pytest

from .fixtures import build_tiny_model_dir


@pytest.fixture(scope="session")
def tiny_model_dir(tmp_path_factory) -> str:
    return build_tiny_model_dir(str(tmp_path_factory.mktemp("tiny-model")))
