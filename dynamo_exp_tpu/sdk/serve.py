"""Graph supervisor: one process per service worker, TPU chips allocated.

Reference parity: ``deploy/dynamo/sdk/cli/serving.py:58-187`` (circus
arbiter with one watcher per service, GPU allocation, per-watcher env) —
rebuilt on plain subprocesses with restart-with-backoff.

    python -m dynamo_exp_tpu.sdk.serve pkg.module:RootClass \
        [-f config.yaml] [--coordinator HOST:PORT | --start-coordinator] \
        [--service-name OnlyThisOne] [--tpu-chips N]
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import logging
import os
import signal
import sys
import time

logger = logging.getLogger("dynamo_exp_tpu.sdk.serve")

MAX_RESTARTS = 3
RESTART_WINDOW_S = 60.0


class Watcher:
    """One service worker process, restarted on unexpected death."""

    def __init__(self, spec, worker_idx: int, argv: list[str], env: dict[str, str]):
        self.spec = spec
        self.worker_idx = worker_idx
        self.argv = argv
        self.env = env
        self.proc: asyncio.subprocess.Process | None = None
        self.restarts: list[float] = []
        self.stopping = False

    @property
    def name(self) -> str:
        return f"{self.spec.name}[{self.worker_idx}]"

    async def start(self) -> None:
        self.proc = await asyncio.create_subprocess_exec(
            *self.argv, env={**os.environ, **self.env}
        )
        logger.info("started %s (pid %d)", self.name, self.proc.pid)

    async def supervise(self) -> None:
        while not self.stopping:
            rc = await self.proc.wait()
            if self.stopping:
                return
            now = time.monotonic()
            self.restarts = [
                t for t in self.restarts if now - t < RESTART_WINDOW_S
            ] + [now]
            if len(self.restarts) > MAX_RESTARTS:
                raise RuntimeError(
                    f"{self.name} crashed {len(self.restarts)} times in "
                    f"{RESTART_WINDOW_S:.0f}s (rc={rc}); giving up"
                )
            logger.warning("%s exited rc=%s; restarting", self.name, rc)
            await asyncio.sleep(min(2 ** (len(self.restarts) - 1), 10))
            await self.start()

    async def stop(self, timeout: float = 20.0) -> None:
        self.stopping = True
        if self.proc is None or self.proc.returncode is not None:
            return
        self.proc.terminate()  # SIGTERM -> graceful drain in the child
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(self.proc.wait(), timeout)
        if self.proc.returncode is None:
            self.proc.kill()
            await self.proc.wait()


class Supervisor:
    """Dynamic watcher set over a service graph.

    Beyond the static arbiter the reference builds on circus, this one
    takes live scale commands — the planner's LocalConnector drives
    ``add_worker``/``remove_worker`` through a control endpoint on the
    coordinator (reference parity:
    ``components/planner/src/dynamo/planner/circusd.py`` add/remove
    watchers via the circus control socket).
    """

    def __init__(
        self, target: str, graph, config, allocator, endpoint: str,
        multihost_argv: list[str] | None = None,
    ):
        self.target = target
        self.specs = {s.name: s for s in graph}
        self.config = config
        self.allocator = allocator
        self.endpoint = endpoint
        # Extra serve_service flags for the (single) TPU service when
        # this pod is one host of a multi-host slice.
        self.multihost_argv = multihost_argv
        self.watchers: dict[str, list[Watcher]] = {s.name: [] for s in graph}
        self._next_idx = {s.name: 0 for s in graph}
        self._tasks: dict[Watcher, asyncio.Task] = {}
        self.failed: asyncio.Future | None = None

    def _build_watcher(self, spec) -> Watcher:
        from .config import ENV_VAR

        env = {
            "DYN_RUNTIME_COORDINATOR_ENDPOINT": self.endpoint,
            ENV_VAR: self.config.dumps(),
            **self.allocator.assign(
                spec.name, int(spec.resources.get("tpu", 0))
            ),
        }
        argv = [
            sys.executable,
            "-m",
            "dynamo_exp_tpu.sdk.serve_service",
            self.target,
            "--service-name",
            spec.name,
        ]
        if self.multihost_argv and int(spec.resources.get("tpu", 0)) > 0:
            argv += self.multihost_argv
        idx = self._next_idx[spec.name]
        self._next_idx[spec.name] += 1
        return Watcher(spec, idx, argv, env)

    async def add_worker(self, service_name: str) -> bool:
        from .allocator import AllocationError

        spec = self.specs.get(service_name)
        if spec is None:
            return False
        try:
            w = self._build_watcher(spec)
        except AllocationError as e:
            logger.warning("add_worker(%s): %s", service_name, e)
            return False
        try:
            await w.start()
        except Exception:
            # Spawn failure must return the chips or repeated planner
            # retries would drain the budget permanently.
            self.allocator.release(w.env)
            logger.exception("add_worker(%s): spawn failed", service_name)
            return False
        self.watchers[service_name].append(w)
        self._tasks[w] = asyncio.ensure_future(self._supervise(w))
        return True

    async def remove_worker(self, service_name: str) -> bool:
        """Stop the newest worker of a service (SIGTERM → child drains,
        deregisters, lease-revokes on exit)."""
        ws = self.watchers.get(service_name) or []
        if not ws:
            return False
        w = ws.pop()
        task = self._tasks.pop(w, None)
        if task is not None:
            task.cancel()
        await w.stop()
        self.allocator.release(w.env)
        return True

    def counts(self) -> dict[str, int]:
        return {name: len(ws) for name, ws in self.watchers.items()}

    async def _supervise(self, w: Watcher) -> None:
        try:
            await w.supervise()
        except Exception as exc:  # crash-looped: surface to serve_graph
            if self.failed is not None and not self.failed.done():
                self.failed.set_exception(exc)

    def _initial_workers(self, spec) -> int:
        """YAML ``ServiceArgs: {workers: N}`` overrides the decorator's
        count (reference parity: per-service ServiceArgs in configs)."""
        svc_args = self.config.get(spec.name).get("ServiceArgs") or {}
        return int(svc_args.get("workers", spec.workers))

    async def start_initial(self) -> None:
        self.failed = asyncio.get_running_loop().create_future()
        for spec in self.specs.values():
            for _ in range(self._initial_workers(spec)):
                if not await self.add_worker(spec.name):
                    raise RuntimeError(f"failed to start {spec.name}")

    async def stop_all(self) -> None:
        for t in self._tasks.values():
            t.cancel()
        self._tasks.clear()
        await asyncio.gather(
            *[w.stop() for ws in self.watchers.values() for w in ws],
            return_exceptions=True,
        )

    async def serve_control(self, drt, namespace: str):
        """Control endpoint the planner's LocalConnector talks to:
        {"op": "add"|"remove"|"list", "service": name} → one reply frame
        {"ok": bool, "counts": {service: n}}."""

        async def handler(request: dict, context=None):
            op = request.get("op")
            service = request.get("service", "")
            ok = True
            if op == "add":
                ok = await self.add_worker(service)
            elif op == "remove":
                ok = await self.remove_worker(service)
            elif op != "list":
                ok = False
            yield {"data": {"ok": ok, "counts": self.counts()}}

        ep = drt.namespace(namespace).component("supervisor").endpoint("control")
        return await ep.serve_endpoint(handler)


async def serve_graph(args) -> None:
    from ..runtime.component import DistributedRuntime
    from ..runtime.config import RuntimeConfig
    from ..runtime.transports.coordinator import CoordinatorServer
    from .allocator import TPUAllocator
    from .config import ServiceConfig
    from .serve_service import load_target
    from .service import discover_graph

    root = load_target(args.target)
    graph = discover_graph(root)
    if args.service_name:
        graph = [s for s in graph if s.name == args.service_name]
        if not graph:
            raise SystemExit(f"no service named {args.service_name!r}")

    coordinator = None
    endpoint = args.coordinator
    if args.start_coordinator:
        coordinator = CoordinatorServer("127.0.0.1", args.coordinator_port)
        await coordinator.start()
        endpoint = coordinator.address
        print(f"coordinator on {endpoint}", flush=True)
    if not endpoint:
        raise SystemExit("need --coordinator or --start-coordinator")

    config = ServiceConfig.load(args.config)
    allocator = TPUAllocator(args.tpu_chips)
    multihost_argv: list[str] | None = None
    if getattr(args, "num_nodes", 1) > 1:
        # Multi-host slice: jax.distributed must be joined by the WORKER
        # process that owns the TPU (one process per host), not by this
        # supervisor — the flags are forwarded to the worker's
        # serve_service argv (deploy tier renders one pod per host rank
        # with these flags; reference capability: ray.rs:66-107).
        tpu_specs = [s for s in graph if int(s.resources.get("tpu", 0)) > 0]
        if len(tpu_specs) != 1 or tpu_specs[0].workers != 1:
            raise SystemExit(
                "--num-nodes > 1 needs --service-name selecting exactly one "
                "TPU service with workers=1 (one joined process per host)"
            )
        multihost_argv = [
            "--num-nodes", str(args.num_nodes),
            "--node-rank", str(args.node_rank),
            "--deployment", getattr(args, "deployment", "default"),
            "--dist-port", str(getattr(args, "dist_port", 9911)),
        ]
        if getattr(args, "dist_leader", ""):
            multihost_argv += ["--dist-leader", args.dist_leader]
    sup = Supervisor(
        args.target, graph, config, allocator, endpoint,
        multihost_argv=multihost_argv,
    )
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=endpoint)
    )
    control = None
    try:
        control = await sup.serve_control(drt, graph[0].namespace)
        await sup.start_initial()
        print(
            f"serving {sum(sup.counts().values())} workers: {sup.counts()}",
            flush=True,
        )
        await sup.failed  # runs until a watcher gives up or we're cancelled
    finally:
        await sup.stop_all()
        if control is not None:
            await control.close()
        await drt.close()
        if coordinator is not None:
            await coordinator.close()


def main(argv: list[str] | None = None) -> None:
    # DYN_LOG / DYN_LOGGING_JSONL aware (trace-correlated JSONL lines);
    # service processes inherit DYN_TRACE_FILE for span recording.
    from ..runtime.logging import configure_logging

    configure_logging()
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("target", help="pkg.module:RootClass")
    p.add_argument("-f", "--config", default=None, help="service config YAML")
    p.add_argument("--coordinator", default=os.environ.get("DYN_COORDINATOR", ""))
    p.add_argument("--start-coordinator", action="store_true")
    p.add_argument("--coordinator-port", type=int, default=0)
    p.add_argument("--service-name", default=None, help="run one service only")
    p.add_argument("--tpu-chips", type=int, default=None,
                   help="host chip budget (default: env DYN_TPU_CHIPS or 4)")
    p.add_argument("--num-nodes", type=int, default=1,
                   help="hosts in this service's TPU slice (multi-host)")
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--dist-leader", default="",
                   help="rank-0 host:port; empty = discover via coordinator")
    p.add_argument("--dist-port", type=int, default=9911,
                   help="port rank 0 binds for jax.distributed")
    p.add_argument("--deployment", default="default",
                   help="leader-key namespace for multi-host discovery")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])

    loop = asyncio.new_event_loop()
    task = loop.create_task(serve_graph(args))
    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError, ValueError):
            loop.add_signal_handler(sig, task.cancel)
    try:
        loop.run_until_complete(task)
    except asyncio.CancelledError:
        pass
    finally:
        loop.close()


if __name__ == "__main__":
    main()
