"""KV-page transfer plane: direct TCP between prefill and decode workers.

The reference moves KV blocks GPU→GPU with NIXL/UCX RDMA writes plus a
completion notification (``/root/reference/container/deps/vllm/…patch:
1040-1862``). On TPU there is no peer-to-peer RDMA library; the
equivalent is host-bounce: the prefill engine gathers pages to host
numpy (XLA dynamic-slice + device→host DMA), this plane ships the bytes
over one TCP message, and the decode engine injects them (host→device
DMA + scatter). The two-part codec keeps the payload opaque — one frame
carries every page of a request, so the handoff costs one round trip.

Dtype note: pages travel as raw bytes tagged with the dtype name;
bfloat16 numpy arrays (via ml_dtypes) round-trip through
``tobytes``/``frombuffer`` losslessly.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

import jax.numpy as jnp
import numpy as np

from ..runtime.transports.codec import (
    MsgType,
    TwoPartMessage,
    read_message,
    write_message,
)

logger = logging.getLogger(__name__)


def _dtype_from_name(name: str) -> np.dtype:
    if name == "bfloat16":
        return np.dtype(jnp.bfloat16)
    return np.dtype(name)


def encode_pages(pages: list[tuple[np.ndarray, np.ndarray]]) -> tuple[dict, bytes]:
    """Pack [(k_page, v_page), ...] into (header, payload)."""
    if not pages:
        return {"n_pages": 0, "shape": [], "dtype": "float32"}, b""
    shape = list(pages[0][0].shape)
    dtype = pages[0][0].dtype
    buf = bytearray()
    for k, v in pages:
        buf += np.ascontiguousarray(k).tobytes()
        buf += np.ascontiguousarray(v).tobytes()
    return {"n_pages": len(pages), "shape": shape, "dtype": str(dtype)}, bytes(buf)


def decode_pages(header: dict, payload: bytes) -> list[tuple[np.ndarray, np.ndarray]]:
    n = header["n_pages"]
    if n == 0:
        return []
    shape = tuple(header["shape"])
    dtype = _dtype_from_name(header["dtype"])
    per = int(np.prod(shape)) * dtype.itemsize
    pages = []
    for i in range(n):
        off = i * 2 * per
        k = np.frombuffer(payload, dtype, count=int(np.prod(shape)), offset=off)
        v = np.frombuffer(payload, dtype, count=int(np.prod(shape)), offset=off + per)
        pages.append((k.reshape(shape), v.reshape(shape)))
    return pages


async def send_kv_pages(
    return_addr: str,
    request_id: str,
    first_token: int,
    pages: list[tuple[np.ndarray, np.ndarray]],
    error: str | None = None,
) -> None:
    """Deliver one prefill result (or failure notice) to a decode worker."""
    host, _, port = return_addr.rpartition(":")
    reader, writer = await asyncio.open_connection(host or "127.0.0.1", int(port))
    try:
        if error is not None:
            msg = TwoPartMessage(
                MsgType.ERROR, {"request_id": request_id, "error": error}
            )
        else:
            header, payload = encode_pages(pages)
            header.update({"request_id": request_id, "first_token": first_token})
            msg = TwoPartMessage(MsgType.FRAME, header, payload)
        await write_message(writer, msg)
        # Wait for the ack so the pages are known-delivered before the
        # prefill worker releases/reuses its device pages.
        await read_message(reader)
    finally:
        writer.close()
        with contextlib.suppress(Exception):
            await writer.wait_closed()


class KvPageReceiver:
    """Decode-worker side: accepts prefill results, resolves per-request
    futures. One receiver per decode worker process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._pending: dict[str, asyncio.Future] = {}

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("KV receiver closed"))
        self._pending.clear()

    def expect(self, request_id: str) -> asyncio.Future:
        """Register interest *before* queueing the prefill request, so the
        result can't race past us."""
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        return fut

    def forget(self, request_id: str) -> None:
        self._pending.pop(request_id, None)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        fut = None
        try:
            msg = await read_message(reader)
            rid = msg.header.get("request_id", "")
            fut = self._pending.pop(rid, None)
            if fut is None or fut.done():
                logger.warning("KV pages for unknown request %s dropped", rid)
            elif msg.msg_type == MsgType.ERROR:
                fut.set_exception(RuntimeError(msg.header.get("error", "prefill failed")))
            else:
                pages = decode_pages(msg.header, msg.payload)
                fut.set_result((msg.header["first_token"], pages))
            await write_message(writer, TwoPartMessage(MsgType.COMPLETE, {"ok": True}))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as e:  # noqa: BLE001 - a malformed frame must fail
            # the waiting request *now*, not leave it to time out.
            logger.exception("bad KV transfer frame")
            if fut is not None and not fut.done():
                fut.set_exception(RuntimeError(f"bad KV transfer frame: {e}"))
        finally:
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()
