"""Stream -> full-response aggregation for ``stream=false`` requests.

The service always streams internally; unary responses are folded from
the chunk stream. Capability parity with
``/root/reference/lib/llm/src/protocols/openai/*/aggregator.rs``.
"""

from __future__ import annotations

from typing import AsyncIterator

from .openai import (
    ChatChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatMessage,
    CompletionChoice,
    CompletionChunk,
    CompletionResponse,
    Usage,
)


async def aggregate_chat_stream(
    chunks: AsyncIterator[ChatCompletionChunk],
) -> ChatCompletionResponse:
    pieces: dict[int, list[str]] = {}
    finish: dict[int, str | None] = {}
    roles: dict[int, str] = {}
    usage: Usage | None = None
    meta: ChatCompletionChunk | None = None
    async for chunk in chunks:
        meta = meta or chunk
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            idx = choice.index
            if choice.delta.role:
                roles[idx] = choice.delta.role
            if choice.delta.content:
                pieces.setdefault(idx, []).append(choice.delta.content)
            if choice.finish_reason is not None:
                finish[idx] = choice.finish_reason
    if meta is None:
        raise ValueError("empty response stream")
    indices = sorted(set(pieces) | set(finish) | set(roles)) or [0]
    choices = [
        ChatChoice(
            index=i,
            message=ChatMessage(
                role=roles.get(i, "assistant"), content="".join(pieces.get(i, []))
            ),
            finish_reason=finish.get(i),
        )
        for i in indices
    ]
    return ChatCompletionResponse(
        id=meta.id,
        created=meta.created,
        model=meta.model,
        choices=choices,
        usage=usage,
    )


async def aggregate_completion_stream(
    chunks: AsyncIterator[CompletionChunk],
) -> CompletionResponse:
    pieces: dict[int, list[str]] = {}
    finish: dict[int, str | None] = {}
    usage: Usage | None = None
    meta: CompletionChunk | None = None
    async for chunk in chunks:
        meta = meta or chunk
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            if choice.text:
                pieces.setdefault(choice.index, []).append(choice.text)
            if choice.finish_reason is not None:
                finish[choice.index] = choice.finish_reason
    if meta is None:
        raise ValueError("empty response stream")
    indices = sorted(set(pieces) | set(finish)) or [0]
    choices = [
        CompletionChoice(
            index=i, text="".join(pieces.get(i, [])), finish_reason=finish.get(i)
        )
        for i in indices
    ]
    return CompletionResponse(
        id=meta.id,
        created=meta.created,
        model=meta.model,
        choices=choices,
        usage=usage,
    )
