"""Sim-vs-live validation of tuned candidates (docs/tuning.md).

Before a recommendation ships, the top-K candidates re-run against the
live tiny harness — the same PR 6 calibration bridge the sim suite
uses (a TINY-model engine whose shape mirrors the SimConfig under
test, fronted by the real AdmissionController) — and the live ranking
must agree with the sim ranking (Kendall tau + top-1). A candidate
that only wins in the model is a modeling artifact, not a tuning.

This module necessarily reads wall clocks (it measures a real engine);
the reads are inline-waived for the determinism zone the rest of
``tune/`` lives in.
"""

from __future__ import annotations

from .search import SearchSettings, TuneTarget, evaluate
from . import space


def kendall_tau(a: list[float], b: list[float]) -> float:
    """Rank agreement between two score lists over the same candidates
    (b[i] scores the same candidate as a[i]); 1.0 = identical order,
    -1.0 = reversed. Ties count as discordant half-weight-free (they
    simply don't contribute)."""
    n = len(a)
    if n < 2:
        return 1.0
    conc = disc = 0
    for i in range(n):
        for j in range(i + 1, n):
            da = (a[i] - a[j])
            db = (b[i] - b[j])
            prod = da * db
            if prod > 0:
                conc += 1
            elif prod < 0:
                disc += 1
    total = n * (n - 1) / 2
    return round((conc - disc) / total, 4)


def harness_workload(
    target: TuneTarget,
    seed: int,
    n: int = 10,
    rate_rps: float = 8.0,
    max_prompt: int = 96,
    max_new: int = 24,
):
    """The shared validation workload: the target replayed at tiny-
    harness scale (prompt/output lengths clamped to the harness model
    length) — BOTH sides consume exactly this list, so ranking
    differences come from the configs, never the workload."""
    from dataclasses import replace

    reqs = TuneTarget(
        kind=target.kind,
        fingerprint=target.fingerprint,
        name=target.name or "burst",
        requests=n,
        rate_rps=rate_rps,
        duration_s=max(n / rate_rps, 1.0),
    ).workload(seed)
    return [
        replace(
            r,
            prompt_len=min(r.prompt_len, max_prompt),
            max_tokens=min(max(r.max_tokens, 2), max_new),
            prefix_group=-1,
            prefix_len=0,
        )
        for r in reqs
    ]


async def measure_live(
    overrides: dict,
    workload,
    harness: dict,
    slo_ttft_s: float = 30.0,
    slo_itl_s: float = 2.0,
) -> dict:
    """Run one candidate's live-applicable engine knobs on a tiny real
    engine against the shared workload; score with the same composite
    shape the sim objective uses (1-instance chip-seconds = duration).

    The ``max_inflight`` knob (the edge admission bound) applies here
    too — it is a live deployment surface, just not an ``EngineConfig``
    field: it sizes the AdmissionController fronting the engine, so a
    candidate that sheds in the sim sheds on the harness for the same
    reason. The SLO gates default to harness scale (not the production
    2s/0.2s constants): the tiny engine runs on whatever host CI
    provides, and production-scale gates would make the compliance
    fractions encode host speed rather than config quality — the
    ranking, not the absolute score, is the validated signal."""
    import asyncio
    import time

    from ..engine import EngineConfig, TPUEngine
    from ..http.admission import AdmissionController, RequestShedError
    from ..models import TINY
    from ..parallel import single_device_mesh
    from ..protocols.common import BackendInput, SamplingOptions

    kwargs = dict(harness)
    kwargs.update(space.engine_kwargs_from_overrides(overrides))
    cfg = EngineConfig(
        model=TINY, eos_token_ids=[], kv_dtype="float32", **kwargs
    )
    adm = AdmissionController(
        max_inflight=int(
            overrides.get("max_inflight") or max(len(workload), 4)
        )
    )
    engine = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    engine.start()
    try:
        results: list[dict] = []

        async def one(req, admit: bool = True, record: bool = True) -> None:
            if admit:
                await asyncio.sleep(req.arrival_s)
                try:
                    adm.acquire(req.priority)
                except RequestShedError:
                    results.append({"shed": True})
                    return
            try:
                bi = BackendInput(
                    token_ids=[
                        (17 * req.index + k) % 211 + 3
                        for k in range(req.prompt_len)
                    ],
                    priority=req.priority,
                )
                bi.stop_conditions.max_tokens = req.max_tokens
                bi.stop_conditions.ignore_eos = True
                bi.sampling_options = SamplingOptions(
                    temperature=0.9, seed=1000 + req.index
                )
                start = time.monotonic()  # dynlint: determinism(live validation wall-clock measurement)
                first = last = None
                tokens = 0
                stream = await engine.generate(bi.to_dict())
                async for item in stream:
                    now = time.monotonic()  # dynlint: determinism(live validation wall-clock measurement)
                    if item.get("token_ids"):
                        tokens += len(item["token_ids"])
                        if first is None:
                            first = now
                        last = now
                itl = (
                    (last - first) / (tokens - 1)
                    if first is not None and last is not None and tokens > 1
                    else 0.0
                )
                if record:
                    results.append({
                        "shed": False,
                        "tokens": tokens,
                        "ttft_s": (first - start) if first is not None else 0.0,
                        "itl_s": itl,
                    })
            finally:
                if admit:
                    adm.release()

        # Warm pass: the whole workload shape-for-shape, no admission,
        # no arrival spread, nothing recorded. Every prefill bucket and
        # the decode graph compile HERE, identically for every
        # candidate — otherwise whichever candidate the host still owes
        # a compile donates that stall to its measured duration and the
        # ranking encodes cache state, not config quality.
        await asyncio.gather(
            *[one(r, admit=False, record=False) for r in workload]
        )

        t0 = time.monotonic()  # dynlint: determinism(live validation wall-clock measurement)
        await asyncio.gather(*[one(r) for r in workload])
        duration = max(time.monotonic() - t0, 1e-6)  # dynlint: determinism(live validation wall-clock measurement)
    finally:
        engine.stop()

    done = [r for r in results if not r["shed"]]
    completed = max(len(done), 1)
    ttft_ok = sum(1 for r in done if r["ttft_s"] <= slo_ttft_s) / completed
    itl_ok = sum(1 for r in done if r["itl_s"] <= slo_itl_s) / completed
    tokens = sum(r["tokens"] for r in done)
    goodput_per_chip_s = tokens / duration
    return {
        "score": round(goodput_per_chip_s * ttft_ok * itl_ok, 6),
        "goodput_per_chip_s": round(goodput_per_chip_s, 4),
        "ttft_compliance": round(ttft_ok, 4),
        "itl_compliance": round(itl_ok, 4),
        "completed": len(done),
        "shed": sum(1 for r in results if r["shed"]),
        "chip_seconds": round(duration, 3),
    }


async def validate_candidates(
    candidates: list[dict],
    target: TuneTarget,
    seed: int,
    harness: dict | None = None,
    n: int = 10,
    slo_ttft_s: float = 30.0,
    slo_itl_s: float = 2.0,
) -> dict:
    """Rank the candidates in the sim AND on the live tiny harness over
    one shared clamped workload; report both rankings plus Kendall tau
    and top-1 agreement. ``harness`` is the engine-shape envelope
    (defaults mirror the PR 6 pressure harness, roomier pool).
    ``slo_ttft_s``/``slo_itl_s`` gate the live composite (harness-scale
    defaults; pass large values to rank on goodput alone — a cold-start
    compile stall on a slow host can blow a single inter-token gap past
    any fixed gate and flip a ranking the throughput still decides)."""
    harness = harness or {
        "max_decode_slots": 4,
        "page_size": 8,
        "num_pages": 64,
        "max_model_len": 128,
        "preempt_stall_grace_s": 0.2,
    }
    workload = harness_workload(target, seed, n=n)
    sim_base = {
        "slots_per_instance": harness["max_decode_slots"],
        "pages_per_instance": harness["num_pages"],
        "page_size": harness["page_size"],
        "preempt_stall_grace_s": harness["preempt_stall_grace_s"],
        "max_inflight": max(len(workload), 4),
        "initial_instances": 1,
    }

    sim_settings = SearchSettings(
        seed=seed, base_sim=sim_base, eval_seeds=1
    )
    fixed = TuneTarget(
        kind="synthetic", name="burst", requests=len(workload)
    )

    sim_scores: list[float] = []
    live_scores: list[float] = []
    rows: list[dict] = []
    for i, overrides in enumerate(candidates):
        sim_comp = evaluate(
            overrides, fixed, sim_settings, seed, workload=list(workload)
        )
        live_comp = await measure_live(
            overrides,
            workload,
            harness,
            slo_ttft_s=slo_ttft_s,
            slo_itl_s=slo_itl_s,
        )
        sim_scores.append(sim_comp["score"])
        live_scores.append(live_comp["score"])
        rows.append({
            "candidate": i,
            "overrides": {k: overrides[k] for k in sorted(overrides)},
            "sim": sim_comp,
            "live": live_comp,
        })

    tau = kendall_tau(sim_scores, live_scores)
    top1 = (
        sim_scores.index(max(sim_scores))
        == live_scores.index(max(live_scores))
        if sim_scores
        else True
    )
    return {
        "candidates": rows,
        "sim_scores": sim_scores,
        "live_scores": live_scores,
        "kendall_tau": tau,
        "top1_agreement": top1,
        "agreed": top1 and tau >= 0.0,
    }
