from .indexer import KvIndexer, KvIndexerSharded, RadixIndex
from .metrics_aggregator import KvMetricsAggregator
from .protocols import (
    KV_HIT_RATE_SUBJECT,
    ForwardPassMetrics,
    KvCacheEventData,
    KVHitRateEvent,
    OverlapScores,
    RouterEvent,
    RouterRequest,
    RouterResponse,
    kv_events_subject,
)
from .publisher import KvEventPublisher, KvMetricsPublisher
from .router import KvPushRouter, KvRouter
from .scheduler import (
    DefaultWorkerSelector,
    NoWorkersError,
    ProcessedEndpoints,
    WorkerSelector,
)

__all__ = [
    "KvIndexer",
    "KvIndexerSharded",
    "RadixIndex",
    "KvMetricsAggregator",
    "ForwardPassMetrics",
    "KvCacheEventData",
    "KVHitRateEvent",
    "OverlapScores",
    "RouterEvent",
    "RouterRequest",
    "RouterResponse",
    "kv_events_subject",
    "KV_HIT_RATE_SUBJECT",
    "KvEventPublisher",
    "KvMetricsPublisher",
    "KvRouter",
    "KvPushRouter",
    "DefaultWorkerSelector",
    "WorkerSelector",
    "NoWorkersError",
    "ProcessedEndpoints",
]
