from .config import LLAMA_1B, LLAMA_3B, LLAMA_8B, PRESETS, TINY, ModelConfig
from .llama import (
    forward,
    init_kv_cache,
    init_params,
    kv_cache_shardings,
    param_shardings,
)

__all__ = [
    "ModelConfig",
    "TINY",
    "LLAMA_1B",
    "LLAMA_3B",
    "LLAMA_8B",
    "PRESETS",
    "forward",
    "init_params",
    "init_kv_cache",
    "param_shardings",
    "kv_cache_shardings",
]
