"""Composable pipeline: operators chained in front of a sink engine.

Capability parity with the reference pipeline graph
(``/root/reference/lib/runtime/src/pipeline/nodes.rs``): a request flows
frontend -> operator(s) -> backend; each operator can transform the
request on the way down and the response stream on the way up. In JAX
terms this is just function composition over AsyncEngines, so the Python
shape is small.
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator

from .engine import AsyncEngine, AsyncEngineContext, ResponseStream


class Context:
    """Per-request context bag propagated through the pipeline (request id,
    annotations requested by the client, arbitrary values)."""

    def __init__(self, request_id: str | None = None):
        self.engine_context = AsyncEngineContext(request_id)
        self.values: dict[str, Any] = {}

    @property
    def id(self) -> str:
        return self.engine_context.id


class Operator(abc.ABC):
    """A bidirectional transform stage."""

    @abc.abstractmethod
    async def generate(
        self,
        request: Any,
        next_engine: AsyncEngine,
        context: AsyncEngineContext,
    ) -> ResponseStream: ...


class _OperatorEngine(AsyncEngine):
    def __init__(self, op: Operator, next_engine: AsyncEngine):
        self._op = op
        self._next = next_engine

    async def generate(
        self, request: Any, context: AsyncEngineContext | None = None
    ) -> ResponseStream:
        ctx = context or AsyncEngineContext()
        return await self._op.generate(request, self._next, ctx)


def build_pipeline(operators: list[Operator], sink: AsyncEngine) -> AsyncEngine:
    """Chain operators (first = outermost) in front of ``sink``."""
    engine = sink
    for op in reversed(operators):
        engine = _OperatorEngine(op, engine)
    return engine


class MapOperator(Operator):
    """Stateless operator from two plain functions (request map, item map)."""

    def __init__(self, map_request=None, map_response_item=None):
        self._map_req = map_request
        self._map_item = map_response_item

    async def generate(
        self,
        request: Any,
        next_engine: AsyncEngine,
        context: AsyncEngineContext,
    ) -> ResponseStream:
        if self._map_req is not None:
            request = self._map_req(request)
        stream = await next_engine.generate(request, context)

        async def _gen() -> AsyncIterator[Any]:
            async for item in stream:
                yield self._map_item(item) if self._map_item else item

        return ResponseStream(_gen(), context)
