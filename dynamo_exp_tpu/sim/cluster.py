"""The cluster simulator: modeled instances, real policy code.

What is modeled and what is real (docs/simulation.md):

- **Real, imported, unmodified**: edge admission
  (:class:`~dynamo_exp_tpu.http.admission.AdmissionController` — the
  same watermark/priority math, the same instance), decode-instance
  selection (:class:`~dynamo_exp_tpu.kv_router.scheduler
  .DefaultWorkerSelector` over :class:`ForwardPassMetrics`), KV-pressure
  victim policy (:func:`~dynamo_exp_tpu.engine.scheduler
  .select_preemption_victim`), and the planner's decision step
  (:func:`~dynamo_exp_tpu.planner.policy.plan_step` /
  :func:`plan_step_slo`). A policy bug visible in simulation is a bug
  in production code, not in a reimplementation.
- **Modeled**: time. Instances hold work for service times drawn from a
  telemetry-fitted :class:`~.fit.ServiceTimeModel` instead of running a
  forward pass. KV occupancy is page-counted exactly (page size, pool
  size, per-sequence growth) but page *content* doesn't exist.

Modeling approximations (documented because calibration tolerance
depends on them): a decode round samples its per-token interval once
(occupancy at round start, not re-priced as neighbors come and go);
page allocation is greedy-reserving (a round grabs what it can up
front and schedules its stall at the exhaustion instant rather than
allocating page-by-page); preempted work re-enters as a full-context
continuation exactly like the engine's deterministic-resume path.
"""

from __future__ import annotations

import random
import time
from collections import deque
from dataclasses import dataclass, field

from ..engine.scheduler import SeqState, select_preemption_victim
from ..engine.tiering import footprint_pages, select_packed_index
from ..http.admission import (
    AdmissionController,
    RequestShedError,
    ServiceOverloadedError,
)
from ..kv import PrefixIndex
from ..tokens import chain_hash
from ..kv_router.protocols import ForwardPassMetrics, OverlapScores
from ..kv_router.scheduler import (
    DefaultWorkerSelector,
    NoWorkersError,
    ProcessedEndpoints,
)
from ..parallel.multihost import TopologyCoordinate
from ..planner.planner import PlannerConfig
from ..planner.policy import (
    PlannerObservation,
    PlannerState,
    SloTargets,
    arm_decode_grace,
    plan_step,
    plan_step_slo,
)
from ..runtime.reclaim import (
    MIGRATE,
    SequenceSnapshot,
    SurvivorInfo,
    plan_triage,
)
from ..telemetry.slo import SloAttribution, SloConfig
from .core import EventLoop
from .fit import ServiceTimeModel
from .report import SimReport, percentile
from .workload import SimRequest


def _pages(tokens: int, page_size: int) -> int:
    return -(-tokens // page_size) if tokens > 0 else 0


@dataclass
class SimConfig:
    """One simulated deployment. Instance-shape fields mirror
    EngineConfig; edge fields mirror the HTTP AdmissionController; the
    planner fields select and parameterize the shared decision step."""

    seed: int = 0
    # Per-instance engine shape.
    slots_per_instance: int = 8
    pages_per_instance: int = 256
    page_size: int = 16
    preempt_stall_grace_s: float = 0.5
    max_preemptions_per_seq: int = 2
    # Edge admission (one controller fronts the fleet).
    max_inflight: int = 64
    shed_watermark: int | None = None
    # Scale the admission bound with the live fleet (max_inflight /
    # shed_watermark are then per-instance budgets).
    admission_per_instance: bool = False
    # Routing.
    queue_weight: float = 1.0
    # Fleet-wide prefix sharing (docs/prefix_sharing.md): prefix_group
    # requests attach refcounted shared pages behind the same radix-
    # match logic the live engine runs; False models the private-copy
    # baseline (every request pays full pages for its prefix).
    prefix_sharing: bool = True
    # Predictive KV tiering (docs/engine_perf.md "Predictive KV
    # tiering"): footprint-packed admission (the same
    # select_packed_index rule the live scheduler runs), and a modeled
    # G2 host tier of this many pages per instance enabling proactive
    # offload — under KV pressure a cold row's private pages swap to
    # the host tier (restore billed at restore_s_per_page) instead of
    # the row being preempted. 0 host pages = reactive baseline.
    kv_packing: bool = True
    # Mirrors of EngineConfig.packing_scan_limit / packing_max_defers
    # (the autotuner tunes them through the shared knob registry,
    # tune/space.py): waiting-queue prefix scanned per packing pass,
    # and bypasses before a deferred sequence becomes a barrier.
    packing_scan_limit: int = 16
    packing_max_defers: int = 64
    host_pages_per_instance: int = 0
    proactive_offload: bool = True
    # Durable G3 KV (docs/fault_tolerance.md "Durable KV & corruption
    # containment"): a modeled per-host persistent page store fed by
    # parked-shared-block evictions (the live HostKvPool.on_demote ->
    # PersistentKvStore.store path), FIFO-bounded at this many pages.
    # A prefix_group admission whose radix match falls short extends
    # its warm-prefill credit with store-resident chain blocks, billed
    # at g3_restore_s_per_page each instead of their prefill compute.
    # 0 pages = no store (G2-only baseline).
    g3_pages_per_instance: int = 0
    g3_restore_s_per_page: float = 0.0005
    # Restart drill: at this sim time the busiest instance hard-
    # restarts (power cut — no reclaim grace): in-flight work journals
    # over to survivors, the host respawns after provision_s on the
    # SAME modeled disk, and its G3 store re-adopts (the live
    # boot_scan), so returning prefix groups re-attach warm.
    restart_at_s: float | None = None
    # Fleet.
    initial_instances: int = 1
    provision_s: float | None = None  # None -> service model's value
    # Spot reclamation (docs/fault_tolerance.md "Spot reclamation &
    # live migration"): the last ceil(initial * spot_fraction) initial
    # instances run on spot capacity and are reclaimed by a seeded
    # exponential schedule at reclaim_rate_per_min, each with
    # reclaim_grace_s of warning. In-flight sequences triage through
    # the REAL runtime.reclaim.plan_triage planner: live KV migration
    # (billed at migration_bw_bps over kv_bytes_per_page, sequential
    # out of the dying host) lands the prefix on the topology-nearest
    # survivor as admission cache credit; everything else rides the
    # journal (full re-prefill on the least-loaded survivor). A
    # reclaimed spot instance respawns after provision_s, and spot
    # chip-seconds bill at spot_cost_factor — billed_chip_seconds is
    # the "fraction of the cost" claim.
    spot_fraction: float = 0.0
    reclaim_rate_per_min: float = 0.0
    reclaim_grace_s: float = 5.0
    reclaim_margin_s: float = 0.25
    migration_bw_bps: float = 100e6
    kv_bytes_per_page: int = 2 << 20
    spot_cost_factor: float = 0.3
    # Planner: None (fixed fleet) | "reactive" | "slo".
    planner: str | None = None
    planner_cfg: PlannerConfig | None = None
    slo: SloTargets | None = None
    # Service times.
    service: ServiceTimeModel = field(default_factory=ServiceTimeModel)
    # Bookkeeping.
    record_events: bool = True
    max_events: int = 50_000_000


class _SimSeq:
    """One in-flight request. Carries exactly the policy surface
    :func:`select_preemption_victim` reads (state / pending_finish /
    extract_cb / preemptions / priority / submitted_at) plus the sim's
    own timing state — the real victim policy runs on these objects."""

    __slots__ = (
        "req", "state", "pending_finish", "extract_cb", "preemptions",
        "priority", "submitted_at", "instance", "epoch", "pages",
        "prompt_len", "remaining", "delivered", "round_budget",
        "gen_round", "itl", "decode_start", "first_token_at", "stalled",
        "stall_epoch", "cap_hit", "cached_tokens", "shared_hashes",
        "shared_page_count", "packing_defers", "swapped", "swap_pages",
        # Anatomy rollup marks (SimReport.anatomy): last admission,
        # preemption-limbo start (0 = not preempted-waiting), and when
        # this life's decode began (0 = still prefilling).
        "admitted_at", "preempted_at", "decode_began",
        # Spot reclamation: True while this life is a live-migrated
        # continuation whose cache credit is still unconsumed.
        "migrated",
        # Durable G3 KV: modeled store-fetch seconds owed for the
        # G3-restored share of cached_tokens (billed with the credit).
        "g3_restore_s",
    )

    def __init__(self, req: SimRequest, now: float):
        self.req = req
        self.state = SeqState.WAITING
        self.pending_finish = None
        self.extract_cb = None
        self.preemptions = 0
        self.priority = req.priority
        self.submitted_at = now
        self.instance: "_SimInstance | None" = None
        self.epoch = 0  # bumped on preemption; stale events no-op
        self.pages = 0
        self.prompt_len = req.prompt_len
        self.remaining = req.max_tokens
        self.delivered = 0
        self.round_budget = 0
        self.gen_round = 0
        self.itl = 0.0
        self.decode_start = 0.0
        self.first_token_at = 0.0
        self.stalled = False
        self.stall_epoch = 0  # bumped on each hard stall; stale grace no-ops
        self.cap_hit = False
        self.cached_tokens = 0
        # Prefix sharing: block hashes this sequence holds refs on, and
        # how many of its ``pages`` they back (the rest are private).
        self.shared_hashes: list[int] = []
        self.shared_page_count = 0
        # Predictive tiering: packed-admission bypass count, and the
        # proactive-offload swap state (private pages parked in the
        # modeled host tier awaiting swap-in).
        self.packing_defers = 0
        self.swapped = False
        self.swap_pages = 0
        self.admitted_at = 0.0
        self.preempted_at = 0.0
        self.decode_began = 0.0
        self.migrated = False
        self.g3_restore_s = 0.0


class _SimInstance:
    __slots__ = (
        "id", "cfg", "waiting", "bound", "stall_queue", "pages_free",
        "metrics", "draining", "prefix_index", "shared_refs", "parked",
        "born_at", "preemptions", "host_free", "swap_queue",
        "spot", "topo", "g3",
    )

    def __init__(self, iid: int, cfg: SimConfig, now: float):
        self.id = iid
        self.cfg = cfg
        self.waiting: deque[_SimSeq] = deque()
        self.bound: list[_SimSeq] = []  # PREFILL + ACTIVE (slot holders)
        self.stall_queue: list[_SimSeq] = []  # hard-stalled, FIFO
        self.pages_free = cfg.pages_per_instance
        self.draining = False
        self.born_at = now
        self.preemptions = 0  # per-instance share of report.preemptions
        # Predictive tiering: modeled G2 host-tier capacity and the
        # FIFO of proactively offloaded rows awaiting swap-in.
        self.host_free = cfg.host_pages_per_instance
        self.swap_queue: list[_SimSeq] = []
        # Spot reclamation: capacity class, and a deterministic modeled
        # topology coordinate (4 hosts per slice) so the triage
        # planner's topology-nearest selector has real distances to
        # fold in.
        self.spot = False
        self.topo = TopologyCoordinate(slice_id=iid // 4, host=iid % 4)
        # Prefix sharing (docs/prefix_sharing.md): the SAME radix index
        # the live page manager matches against, over synthetic per-
        # group block chains; refcounts per resident block, plus the
        # zero-ref parked set (counted free; evicted LRU-first as
        # allocations consume the pool — the live reclaimable LRU).
        self.prefix_index = PrefixIndex()
        self.shared_refs: dict[int, int] = {}
        self.parked: dict[int, None] = {}  # insertion order = LRU
        # Durable G3 KV: the modeled persistent store (insertion order
        # = FIFO eviction at g3_pages_per_instance). Dies with the host
        # on reclaim/retire; the restart drill hands it to the respawn
        # (same disk, live boot_scan re-adoption).
        self.g3: dict[int, None] = {}
        # One mutable metrics object per instance: the router reads it
        # in place (no per-arrival allocation at fleet scale).
        self.metrics = ForwardPassMetrics(
            request_total_slots=cfg.slots_per_instance,
            kv_total_blocks=cfg.pages_per_instance,
        )

    def refresh_metrics(self) -> ForwardPassMetrics:
        m = self.metrics
        m.request_active_slots = len(self.bound)
        m.num_requests_waiting = len(self.waiting)
        used = self.cfg.pages_per_instance - self.pages_free
        m.kv_active_blocks = used
        m.gpu_cache_usage_perc = used / self.cfg.pages_per_instance
        return m

    @property
    def idle(self) -> bool:
        return not self.bound and not self.waiting


class ClusterSim:
    """Deterministic replay of a workload through the real policies.

    One instance = one aggregated (prefill+decode) engine; the fleet
    starts at ``initial_instances`` and moves only by planner decisions.
    ``run()`` drains the workload and returns a :class:`SimReport`."""

    def __init__(self, cfg: SimConfig, workload):
        self.cfg = cfg
        self.loop = EventLoop()
        # Independent streams so adding a service-time draw never
        # perturbs routing tie-breaks (and vice versa).
        self.rng_service = random.Random(cfg.seed)
        self.selector = DefaultWorkerSelector(
            rng=random.Random(cfg.seed ^ 0x5EED), queue_weight=cfg.queue_weight
        )
        self.admission = AdmissionController(
            max_inflight=cfg.max_inflight, shed_watermark=cfg.shed_watermark
        )
        self._base_inflight = self.admission.max_inflight
        self._base_watermark = self.admission.shed_watermark
        self.instances: dict[int, _SimInstance] = {}
        self._provisioning = 0
        self._next_iid = 0
        self._workload = iter(workload)
        self._last_arrival = -1.0
        self._stream_done = False
        self._open = 0  # admitted, not yet finished
        self.report = SimReport()
        self._ttfts: list[float] = []
        self._itls: list[float] = []
        # Per-adjustment-interval planner sample window (KV only; the
        # latency window lives in the shared SLO attribution below).
        self._kv_samples: list[float] = []
        self._plan_state = PlannerState()
        self._pcfg = cfg.planner_cfg or PlannerConfig()
        self._slo = cfg.slo or SloTargets()
        # Shared SLO/goodput attribution (telemetry/slo.py): the very
        # class the live HTTP edge feeds and the live planner reads —
        # the sim's SimReport goodput/violation counts and its
        # plan_step_slo pressure window go through it verbatim, closing
        # the live<->sim calibration loop (docs/observability.md).
        self.slo_attr = SloAttribution(
            SloConfig(
                ttft_s=self._slo.ttft_p99_slo_s or None,
                itl_s=self._slo.itl_p99_slo_s or None,
            )
        )
        self._chip_seconds = 0.0
        self._chips_since = 0.0
        # Spot reclamation: its own rng stream (adding a reclaim draw
        # must never perturb routing or service times), billed cost
        # accounting, and the count of spot respawns in flight.
        self._rng_reclaim = random.Random(cfg.seed ^ 0x5B07)
        self._billed_chip_seconds = 0.0
        self._provisioning_spot = 0
        # Request-anatomy rollup (telemetry/anatomy.py component names;
        # SimReport.anatomy): sim-clock component totals across all
        # requests, accumulated at admission / prefill-done / preempt /
        # finish — the sim-side mirror of the engine's anatomy_totals
        # so fingerprint-replay calibration can compare shapes.
        self._anatomy = dict.fromkeys(
            ("queue_wait", "prefill_compute", "decode_compute",
             "preemption"),
            0.0,
        )
        # Prefix sharing: lazily built synthetic block-hash chain per
        # prefix group (chain_hash keeps it deterministic per group id,
        # independent of arrival order), plus resident-shared-page
        # accounting for the report.
        self._prefix_chains: dict[int, list[int]] = {}
        self._shared_resident = 0  # live + parked shared blocks, fleet-wide
        self.event_log: list[str] = []
        n_init = max(cfg.initial_instances, 1)
        n_spot = (
            max(min(round(n_init * cfg.spot_fraction), n_init), 1)
            if cfg.spot_fraction > 0
            else 0
        )
        for i in range(n_init):
            # The LAST n_spot initial instances are spot capacity.
            self._spawn_ready(spot=i >= n_init - n_spot)
        self._resize_admission()

    # ------------------------------------------------------------ logging
    def _log(self, fmt: str, *args) -> None:
        # %-lazy so a million-user run with record_events=False never
        # pays per-event string formatting.
        if self.cfg.record_events:
            msg = fmt % args if args else fmt
            self.event_log.append(f"{self.loop.now:.6f} {msg}")

    # ---------------------------------------------------- prefix sharing
    def _group_hashes(self, group: int, n: int) -> list[int]:
        """First ``n`` synthetic chained block hashes of a prefix group
        (the sim's stand-in for real token-block chains — same chain
        function, deterministic per group id)."""
        chain = self._prefix_chains.setdefault(group, [])
        while len(chain) < n:
            parent = chain[-1] if chain else None
            chain.append(chain_hash(parent, (group << 20) | len(chain)))
        return chain[:n]

    def _take_pages(self, inst: _SimInstance, n: int) -> None:
        """Consume pool pages, evicting parked (zero-ref, still-indexed)
        shared blocks LRU-first once free pages no longer cover them —
        the live manager's reclaimable-LRU eviction."""
        inst.pages_free -= n
        while inst.parked and len(inst.parked) > inst.pages_free:
            h = next(iter(inst.parked))
            del inst.parked[h]
            inst.prefix_index.remove(h)
            self._shared_resident -= 1
            # Durable G3 KV: the evicted cold block demotes to the
            # modeled persistent store (live HostKvPool.on_demote),
            # refreshed to the FIFO tail if already resident.
            if self.cfg.g3_pages_per_instance > 0:
                inst.g3.pop(h, None)
                inst.g3[h] = None
                while len(inst.g3) > self.cfg.g3_pages_per_instance:
                    inst.g3.pop(next(iter(inst.g3)))

    def _release_shared(self, inst: _SimInstance, seq: _SimSeq) -> None:
        """Drop the sequence's refs on its shared blocks; zero-ref
        blocks park (page counted free again, block still matchable
        until evicted)."""
        for h in seq.shared_hashes:
            left = inst.shared_refs.get(h, 0) - 1
            if left > 0:
                inst.shared_refs[h] = left
            else:
                inst.shared_refs.pop(h, None)
                inst.parked[h] = None
                inst.pages_free += 1
        seq.shared_hashes = []
        seq.shared_page_count = 0

    def _note_prefix_resident(self, inst: _SimInstance, seq: _SimSeq) -> None:
        """Baseline (prefix_sharing=False) bookkeeping: record the
        group's blocks for routing overlap and set the warm-prefill
        credit, with no page accounting (every request pays full
        pages)."""
        ps = self.cfg.page_size
        n_shared = min(seq.req.prefix_len, seq.prompt_len) // ps
        hashes = self._group_hashes(seq.req.prefix_group, n_shared)
        matched = inst.prefix_index.match_hashes(hashes)
        parent = hashes[len(matched) - 1] if matched else None
        for h in hashes[len(matched) :]:
            inst.prefix_index.insert(parent, h)
            parent = h
        seq.cached_tokens = min(len(matched) * ps, seq.prompt_len - 1)

    def _attach_prefix(self, inst: _SimInstance, seq: _SimSeq) -> bool:
        """Admission-time radix match + shared-page attach for a
        prefix_group request (mirrors KvPageManager.allocate_sequence:
        attach resident blocks refcounted, register the rest as this
        request's to fill, COW when a resident block extends the
        prompt's partial tail). Returns False when the pool can't cover
        the request right now."""
        cfg = self.cfg
        ps = cfg.page_size
        total = _pages(seq.prompt_len, ps)
        n_shared = min(seq.req.prefix_len, seq.prompt_len) // ps
        hashes = self._group_hashes(seq.req.prefix_group, n_shared + 1)
        matched = inst.prefix_index.match_hashes(hashes[:n_shared])
        new = hashes[len(matched) : n_shared]
        revive = [h for h in matched if h in inst.parked]
        cow = (
            seq.req.prefix_len >= seq.prompt_len
            and seq.prompt_len % ps != 0
            and len(matched) == n_shared
            and len(inst.prefix_index.match_hashes(hashes[: n_shared + 1]))
            == n_shared + 1
        )
        need = len(new) + len(revive) + (total - n_shared)
        if need > inst.pages_free:
            return False
        for h in revive:
            del inst.parked[h]
        self._take_pages(inst, need)
        parent = hashes[len(matched) - 1] if matched else None
        for h in new:
            inst.prefix_index.insert(parent, h)
            parent = h
            self._shared_resident += 1
            self.report.shared_pages_peak = max(
                self.report.shared_pages_peak, self._shared_resident
            )
        for h in matched + new:
            inst.shared_refs[h] = inst.shared_refs.get(h, 0) + 1
        seq.shared_hashes = matched + new
        seq.shared_page_count = n_shared
        seq.pages = total
        # Durable G3 KV: blocks past the radix match whose chain
        # continues in the modeled persistent store restore instead of
        # re-prefilling — credit extends over them, billed at
        # g3_restore_s_per_page each when the credit is consumed
        # (the live G3 fetch -> G2 promote on the admission path).
        g3_restored = 0
        if cfg.g3_pages_per_instance > 0:
            for h in new:
                if h not in inst.g3:
                    break
                g3_restored += 1
        seq.cached_tokens = min(
            (len(matched) + g3_restored) * ps, seq.prompt_len - 1
        )
        if g3_restored:
            seq.g3_restore_s = g3_restored * cfg.g3_restore_s_per_page
            self.report.g3_restored_pages += g3_restored
        if cow:
            self.report.cow_copies += 1
            seq.cached_tokens = seq.prompt_len - 1
        # Same accounting as KvPageManager.prefix_hits["shared"]: full-
        # block attaches plus the COW partial-tail attach (calibration
        # compares these counts exactly).
        self.report.shared_attached_pages += len(matched) + (1 if cow else 0)
        return True

    # ------------------------------------------------------------ fleet
    def _chips(self) -> int:
        return len(self.instances) + self._provisioning

    def _account_chips(self) -> None:
        now = self.loop.now
        dt = now - self._chips_since
        self._chip_seconds += self._chips() * dt
        # Billed cost: spot time (live spot instances + spot respawns
        # in flight) at spot_cost_factor, the rest at on-demand parity.
        n_spot = (
            sum(1 for i in self.instances.values() if i.spot)
            + self._provisioning_spot
        )
        self._billed_chip_seconds += (
            (self._chips() - n_spot) + n_spot * self.cfg.spot_cost_factor
        ) * dt
        self._chips_since = now

    def _spawn_ready(self, spot: bool = False) -> _SimInstance:
        self._account_chips()
        iid = self._next_iid
        self._next_iid += 1
        inst = _SimInstance(iid, self.cfg, self.loop.now)
        inst.spot = spot
        self.instances[iid] = inst
        self.report.max_instances = max(
            self.report.max_instances, len(self.instances)
        )
        self._resize_admission()
        self._log("instance %d ready", iid)
        return inst

    def _provision(self) -> None:
        self._account_chips()
        self._provisioning += 1
        delay = (
            self.cfg.provision_s
            if self.cfg.provision_s is not None
            else self.cfg.service.provision_s
        )
        self.loop.after(delay, self._on_instance_ready)
        self._log("instance provisioning")

    def _on_instance_ready(self) -> None:
        # Bill the provision window while the chip still counts as
        # provisioning — decrementing first would hand every scale-up a
        # free provision_s of chip time and bias the planner comparison.
        self._account_chips()
        self._provisioning -= 1
        self._spawn_ready()

    def _retire(self, inst: _SimInstance) -> None:
        self._account_chips()
        del self.instances[inst.id]
        self._resize_admission()
        self._log("instance %d retired", inst.id)

    def _resize_admission(self) -> None:
        if not self.cfg.admission_per_instance:
            return
        n = max(len(self.instances), 1)
        self.admission.resize(
            self._base_inflight * n, self._base_watermark * n
        )

    def _routable(self) -> list[_SimInstance]:
        return [i for i in self.instances.values() if not i.draining]

    # ----------------------------------------------------------- arrivals
    def _schedule_next_arrival(self) -> None:
        req = next(self._workload, None)
        if req is None:
            self._stream_done = True
            return
        if req.arrival_s < self._last_arrival:
            raise ValueError("workload arrivals must be non-decreasing")
        self._last_arrival = req.arrival_s
        self.loop.at(req.arrival_s, self._on_arrival, req)

    def _on_arrival(self, req: SimRequest) -> None:
        self._schedule_next_arrival()
        self.report.submitted += 1
        try:
            self.admission.acquire(req.priority)
        except ServiceOverloadedError:
            self.report.shed_503 += 1
            self._log("req %d shed 503", req.index)
            return
        except RequestShedError:
            self.report.shed_429 += 1
            self._log("req %d shed 429", req.index)
            return
        candidates = self._routable()
        endpoints = ProcessedEndpoints(
            metrics={i.id: i.refresh_metrics() for i in candidates}
        )
        overlaps = OverlapScores()
        if req.prefix_group >= 0:
            # Real per-instance index coverage — the router walks the
            # same radix trees admissions registered into, exactly like
            # the live KV router over worker prefix indexes.
            q = self._group_hashes(
                req.prefix_group,
                min(req.prefix_len, req.prompt_len) // self.cfg.page_size,
            )
            overlaps = OverlapScores(
                scores={
                    i.id: n
                    for i in candidates
                    if (n := i.prefix_index.coverage_blocks(q)) > 0
                }
            )
        try:
            wid, overlap_blocks = self.selector.select_worker(
                endpoints,
                overlaps,
                req.prompt_len,
                self.cfg.page_size,
            )
        except NoWorkersError:
            self.report.errors += 1
            self.admission.release()
            self._log("req %d error no-workers", req.index)
            return
        inst = self.instances[wid]
        seq = _SimSeq(req, self.loop.now)
        seq.instance = inst
        self._open += 1
        inst.waiting.append(seq)
        self._log("req %d -> inst %d (overlap %d)", req.index, wid, overlap_blocks)
        self._pump(inst)

    # ---------------------------------------------------------- admission
    def _pick_waiting(self, inst: _SimInstance) -> _SimSeq:
        """The next admission candidate: the head under plain first-fit
        or — with footprint packing on — the first waiting sequence
        whose lifetime forecast fits the free pool, through the SAME
        :func:`~dynamo_exp_tpu.engine.tiering.select_packed_index` rule
        the live scheduler runs (priority and starvation guards
        included)."""
        if not self.cfg.kv_packing or len(inst.waiting) <= 1:
            return inst.waiting[0]
        ps = self.cfg.page_size
        cand = []
        entries = []
        for i, s in enumerate(inst.waiting):
            if i >= self.cfg.packing_scan_limit:
                break
            total = footprint_pages(s.prompt_len, s.remaining, ps)
            resident = 0
            if self.cfg.prefix_sharing and s.req.prefix_group >= 0:
                n_shared = min(s.req.prefix_len, s.prompt_len) // ps
                resident = len(
                    inst.prefix_index.match_hashes(
                        self._group_hashes(s.req.prefix_group, n_shared)
                    )
                )
            fits = max(total - resident, 0) <= inst.pages_free
            cand.append(s)
            entries.append((fits, s.priority, s.packing_defers))
        idx = select_packed_index(
            entries, max_defers=self.cfg.packing_max_defers
        )
        if idx is None or idx == 0:
            return inst.waiting[0]
        for s in cand[:idx]:
            s.packing_defers += 1
        return cand[idx]

    @staticmethod
    def _remove_waiting(inst: _SimInstance, seq: _SimSeq) -> None:
        for i, s in enumerate(inst.waiting):
            if s is seq:
                del inst.waiting[i]
                return

    def _pump(self, inst: _SimInstance) -> None:
        """Engine-side admission: bind waiting work to free slots while
        pages allow. Mirrors the live loop's `_kv_pressure` gate —
        nothing is admitted while any bound row is hard-stalled or
        swapped out, so newcomers can't steal pages preemption (or a
        pending swap-in) is waiting for."""
        cfg = self.cfg
        while (
            inst.waiting
            and not inst.stall_queue
            and not inst.swap_queue
            and len(inst.bound) < cfg.slots_per_instance
        ):
            seq = self._pick_waiting(inst)
            capacity_tokens = cfg.pages_per_instance * cfg.page_size
            if seq.prompt_len > capacity_tokens:
                # A prompt bigger than the whole pool can never be
                # allocated — reject (finish=error) instead of waiting
                # forever, exactly like Scheduler.admit_next.
                self._remove_waiting(inst, seq)
                self._finish(seq, "error")
                continue
            if cfg.prefix_sharing and seq.req.prefix_group >= 0:
                if not self._attach_prefix(inst, seq):
                    return  # pool exhausted; retry after a release
                self._remove_waiting(inst, seq)
            else:
                need = _pages(seq.prompt_len, cfg.page_size) - seq.pages
                if need > inst.pages_free:
                    return  # pool exhausted; retry after a release
                self._remove_waiting(inst, seq)
                self._take_pages(inst, max(need, 0))
                seq.pages += max(need, 0)
                if seq.req.prefix_group >= 0:
                    # Private-copy baseline: full pages, but overlap
                    # routing and warm-prefill credit stay (a routing-
                    # only index, never page-accounted or evicted) so
                    # the sharing A/B isolates page residency, not a
                    # routing-policy change.
                    self._note_prefix_resident(inst, seq)
            # Anatomy: close the queue-wait (first admission) or
            # preemption-limbo (re-admission) segment.
            if seq.preempted_at:
                self._anatomy["preemption"] += self.loop.now - seq.preempted_at
                seq.preempted_at = 0.0
            else:
                self._anatomy["queue_wait"] += self.loop.now - seq.submitted_at
            seq.admitted_at = self.loop.now
            seq.state = SeqState.PREFILL
            inst.bound.append(seq)
            prefill_tokens = seq.prompt_len
            # Cache credit applies on first admission (router overlap)
            # or when a live migration just parked this life's prefix on
            # this instance; the credit is consumed here exactly once.
            restore_s = 0.0
            if seq.cached_tokens and (seq.preemptions == 0 or seq.migrated):
                prefill_tokens = max(seq.prompt_len - seq.cached_tokens, 1)
                # G3-restored blocks skip prefill compute but pay the
                # modeled store-fetch time, serialized ahead of the
                # residual prefill (the live restore-before-compute
                # upload ordering).
                restore_s = seq.g3_restore_s
            seq.migrated = False
            seq.g3_restore_s = 0.0
            delay = restore_s + cfg.service.prefill_time(
                prefill_tokens, self.rng_service
            )
            self.loop.after(delay, self._on_prefill_done, seq, seq.epoch)

    # ------------------------------------------------------------- decode
    def _coverable(self, seq: _SimSeq) -> int:
        """Tokens this round's held pages can still produce. The final
        sampled token rides out without its KV written (engine
        semantics), hence the +1."""
        return seq.pages * self.cfg.page_size - seq.prompt_len + 1

    def _on_prefill_done(self, seq: _SimSeq, epoch: int) -> None:
        if seq.epoch != epoch or seq.state is not SeqState.PREFILL:
            return
        cfg = self.cfg
        inst = seq.instance
        seq.state = SeqState.ACTIVE
        # Anatomy: the prefill segment just closed; decode begins.
        self._anatomy["prefill_compute"] += self.loop.now - seq.admitted_at
        seq.decode_began = self.loop.now
        if not seq.first_token_at:
            seq.first_token_at = self.loop.now
            ttft = self.loop.now - seq.req.arrival_s
            self._ttfts.append(ttft)
            self.slo_attr.observe_ttft(ttft)
        rows = sum(1 for s in inst.bound if s.state is SeqState.ACTIVE)
        seq.itl = cfg.service.decode_itl(
            rows, cfg.slots_per_instance, self.rng_service
        )
        seq.decode_start = self.loop.now
        seq.gen_round = 0
        capacity_tokens = cfg.pages_per_instance * cfg.page_size
        max_by_cap = capacity_tokens - seq.prompt_len + 1
        seq.round_budget = min(seq.remaining, max(max_by_cap, 0))
        seq.cap_hit = seq.round_budget < seq.remaining
        self._reserve_and_schedule(seq)

    def _grab_round_pages(self, seq: _SimSeq) -> int:
        """Take as many of the round's still-needed pages as are free;
        returns the number grabbed."""
        cfg = self.cfg
        inst = seq.instance
        need_total = _pages(
            seq.prompt_len + max(seq.round_budget - 1, 0), cfg.page_size
        )
        grab = min(max(need_total - seq.pages, 0), inst.pages_free)
        self._take_pages(inst, grab)
        seq.pages += grab
        return grab

    def _schedule_round_progress(self, seq: _SimSeq) -> bool:
        """Schedule the round's completion (fully covered) or its next
        stall point; False when the held pages can't feed even the next
        token."""
        coverable = self._coverable(seq)
        left = seq.round_budget - seq.gen_round
        if coverable >= seq.round_budget:
            self.loop.after(
                left * seq.itl, self._on_decode_done, seq, seq.epoch
            )
        elif coverable > seq.gen_round:
            self.loop.after(
                (coverable - seq.gen_round) * seq.itl,
                self._on_stall,
                seq,
                seq.epoch,
                coverable,
            )
        else:
            return False
        return True

    def _reserve_and_schedule(self, seq: _SimSeq) -> None:
        self._grab_round_pages(seq)
        if not self._schedule_round_progress(seq):
            self._hard_stall(seq)

    def _on_stall(self, seq: _SimSeq, epoch: int, gen_now: int) -> None:
        if seq.epoch != epoch or seq.state is not SeqState.ACTIVE:
            return
        seq.gen_round = min(gen_now, seq.round_budget)
        self._reserve_and_schedule(seq)

    def _hard_stall(self, seq: _SimSeq) -> None:
        """The row cannot feed its next token: start the preemption
        grace clock (the engine's `stalled_since`). With proactive
        offload enabled (a modeled host tier), a cold row's private
        pages swap out immediately — the live engine's
        ``proactive_offload_grace_s=0`` default — so the grace clock
        usually never expires and preemption stays the fallback."""
        if seq.stalled:
            return
        seq.stalled = True
        # A resume (pages fed) then re-stall within the same epoch must
        # get a FULL grace window (the engine re-sets stalled_since), so
        # each stall gets its own generation and the previous stall's
        # still-pending timer no-ops instead of firing early.
        seq.stall_epoch += 1
        inst = seq.instance
        inst.stall_queue.append(seq)
        self._log("req %d hard-stalled on inst %d", seq.req.index, inst.id)
        if (
            self.cfg.proactive_offload
            and self.cfg.host_pages_per_instance > 0
            and self._proactive_swap(inst)
        ):
            self._feed_stalled(inst)
            if not seq.stalled:
                return  # swap freed enough; no grace clock needed
        grace = self.cfg.preempt_stall_grace_s
        if grace >= 0:
            self.loop.after(
                grace, self._on_grace, seq, seq.epoch, seq.stall_epoch
            )

    def _proactive_swap(self, inst: _SimInstance) -> bool:
        """Swap the coldest eligible row's private pages to the modeled
        host tier (the live ``_swap_out``): lowest priority, youngest,
        not itself stalled or already swapped. Returns True when pages
        were freed."""
        # Mirror of the live victim rule: stalled rows are exempt
        # unless several are starving (then swapping the coldest
        # stalled one feeds the rest).
        n_stalled = len(inst.stall_queue)
        cands = [
            s
            for s in inst.bound
            if s.state is SeqState.ACTIVE
            and not s.swapped
            and (n_stalled >= 2 or not s.stalled)
            and s.pending_finish is None
            and s.extract_cb is None
        ]
        for victim in sorted(
            cands, key=lambda s: (s.priority, -s.submitted_at)
        ):
            freed = victim.pages - victim.shared_page_count
            if freed <= 0 or freed > inst.host_free:
                continue
            # Progress so far this round (the live engine's host view
            # of the row at the swap point).
            gen = victim.gen_round
            if victim.itl > 0:
                gen = min(
                    max(
                        int((self.loop.now - victim.decode_start) / victim.itl),
                        victim.gen_round,
                    ),
                    victim.round_budget,
                )
            victim.gen_round = gen
            victim.epoch += 1  # cancel in-flight round timers
            if victim.stalled:
                victim.stalled = False
                inst.stall_queue.remove(victim)
            victim.swapped = True
            victim.swap_pages = freed
            victim.pages = victim.shared_page_count
            inst.pages_free += freed
            inst.host_free -= freed
            inst.swap_queue.append(victim)
            self.report.proactive_offloads += 1
            self._log(
                "req %d proactively offloaded on inst %d (%d pages)",
                victim.req.index, inst.id, freed,
            )
            return True
        return False

    def _on_swap_resumed(self, seq: _SimSeq, epoch: int) -> None:
        """Restore landed (host→device scatter billed): the row
        resumes its round exactly where it left off."""
        if seq.epoch != epoch or seq.state is not SeqState.ACTIVE:
            return
        seq.decode_start = self.loop.now - seq.gen_round * seq.itl
        if not self._schedule_round_progress(seq):
            self._hard_stall(seq)

    def _on_grace(self, seq: _SimSeq, epoch: int, stall_epoch: int) -> None:
        if (
            seq.epoch != epoch
            or seq.stall_epoch != stall_epoch
            or not seq.stalled
        ):
            return
        inst = seq.instance
        victim = select_preemption_victim(
            inst.bound, self.cfg.max_preemptions_per_seq
        )
        if victim is None:
            return  # nothing eligible; stalled row waits for a release
        self._preempt(victim)
        self._feed_stalled(inst)
        if seq.stalled:  # one eviction wasn't enough — keep the clock
            self.loop.after(
                self.cfg.preempt_stall_grace_s,
                self._on_grace,
                seq,
                seq.epoch,
                seq.stall_epoch,
            )

    def _preempt(self, victim: _SimSeq) -> None:
        """Evict via the real victim policy and requeue the victim as a
        deterministic continuation of itself (full context as prompt,
        budget reduced), exactly like Scheduler.preempt."""
        inst = victim.instance
        gen = victim.gen_round
        if not victim.stalled and not victim.swapped and victim.itl > 0:
            # decode_start is the round's *virtual* start (rebased on
            # stall-resume), so elapsed/itl = tokens actually produced.
            # A swapped victim's progress was frozen at swap-out.
            gen = min(
                max(
                    int((self.loop.now - victim.decode_start) / victim.itl),
                    victim.gen_round,
                ),
                victim.round_budget,
            )
        if victim.swapped:
            # Preempting a swapped row (swap-in starved too long, or
            # the victim policy chose it): its host-tier reservation
            # returns; the continuation re-prefills from scratch.
            victim.swapped = False
            inst.host_free += victim.swap_pages
            victim.swap_pages = 0
            if victim in inst.swap_queue:
                inst.swap_queue.remove(victim)
        victim.epoch += 1
        victim.delivered += gen
        victim.prompt_len += gen
        victim.remaining -= gen
        victim.preemptions += 1
        inst.pages_free += victim.pages - victim.shared_page_count
        self._release_shared(inst, victim)
        victim.pages = 0
        inst.bound.remove(victim)
        if victim.stalled:
            victim.stalled = False
            inst.stall_queue.remove(victim)
        # Anatomy: close this life's decode segment; limbo starts now.
        if victim.decode_began:
            self._anatomy["decode_compute"] += self.loop.now - victim.decode_began
            victim.decode_began = 0.0
        victim.preempted_at = self.loop.now
        victim.state = SeqState.WAITING
        inst.waiting.append(victim)  # back of the queue, like the engine
        inst.preemptions += 1
        self.report.preemptions += 1
        self._log(
            "req %d preempted on inst %d (%d tokens into the round)",
            victim.req.index, inst.id, gen,
        )

    def _feed_stalled(self, inst: _SimInstance) -> None:
        """Freed pages go to hard-stalled rows first (admission stays
        gated while any remain), then to pending swap-ins (oldest
        first), then to engine admission — the live loop's order."""
        for seq in list(inst.stall_queue):
            if self._grab_round_pages(seq) <= 0:
                continue
            if self._schedule_round_progress(seq):
                seq.stalled = False
                inst.stall_queue.remove(seq)
                # Rebase the round's virtual start so elapsed/itl keeps
                # equaling tokens actually produced — a preemption mid-
                # round must not count the stall dwell as generation.
                seq.decode_start = self.loop.now - seq.gen_round * seq.itl
            # else: partial grab, still starved — keep queue position
            # and the already-armed grace clock.
        for seq in list(inst.swap_queue):
            if seq.swap_pages > inst.pages_free:
                continue
            self._take_pages(inst, seq.swap_pages)
            seq.pages += seq.swap_pages
            inst.host_free += seq.swap_pages
            restore = seq.swap_pages * self.cfg.service.restore_s_per_page
            seq.swap_pages = 0
            seq.swapped = False
            inst.swap_queue.remove(seq)
            self.report.swap_ins += 1
            self._log("req %d swapped back in on inst %d", seq.req.index, inst.id)
            self.loop.after(restore, self._on_swap_resumed, seq, seq.epoch)
        self._pump(inst)

    def _on_decode_done(self, seq: _SimSeq, epoch: int) -> None:
        if seq.epoch != epoch or seq.state is not SeqState.ACTIVE:
            return
        seq.delivered += seq.round_budget
        seq.remaining -= seq.round_budget
        self._finish(seq, "length")

    # ------------------------------------------------------------- finish
    def _finish(self, seq: _SimSeq, reason: str) -> None:
        inst = seq.instance
        seq.epoch += 1
        seq.state = SeqState.FINISHED
        # Anatomy: close whichever segment this request died inside of.
        if seq.preempted_at:
            self._anatomy["preemption"] += self.loop.now - seq.preempted_at
            seq.preempted_at = 0.0
        elif seq.decode_began:
            self._anatomy["decode_compute"] += self.loop.now - seq.decode_began
            seq.decode_began = 0.0
        if inst is not None:
            inst.pages_free += seq.pages - seq.shared_page_count
            self._release_shared(inst, seq)
            seq.pages = 0
            if seq in inst.bound:
                inst.bound.remove(seq)
            if seq.stalled:
                seq.stalled = False
                inst.stall_queue.remove(seq)
            if seq.swapped:
                seq.swapped = False
                inst.host_free += seq.swap_pages
                seq.swap_pages = 0
                if seq in inst.swap_queue:
                    inst.swap_queue.remove(seq)
        self._open -= 1
        self.admission.release()
        if reason == "length":
            self.report.completed += 1
            self.report.completed_tokens += seq.delivered
            if seq.cap_hit:
                self.report.capacity_capped += 1
            itl = None
            if seq.delivered > 1 and seq.first_token_at:
                itl = (self.loop.now - seq.first_token_at) / (
                    seq.delivered - 1
                )
                self._itls.append(itl)
                self.slo_attr.observe_itl(itl)
            # Shared-path attribution: same call the live edge makes
            # per drained stream (shed/errored work is never fed here,
            # so it can't count as goodput — matching the edge).
            ttft = (
                seq.first_token_at - seq.req.arrival_s
                if seq.first_token_at
                else None
            )
            self.slo_attr.count(seq.priority, ttft_s=ttft, itl_s=itl)
        else:
            self.report.errors += 1
        self._log("req %d finished %s (%d tok)", seq.req.index, reason, seq.delivered)
        if inst is not None:
            self._feed_stalled(inst)
            if inst.draining and inst.idle and len(self.instances) > 1:
                self._retire(inst)

    # ---------------------------------------------------- spot reclamation
    def _start_reclaims(self) -> None:
        if self.cfg.spot_fraction > 0 and self.cfg.reclaim_rate_per_min > 0:
            self._schedule_next_reclaim()

    def _schedule_next_reclaim(self) -> None:
        rate = self.cfg.reclaim_rate_per_min / 60.0
        self.loop.after(
            self._rng_reclaim.expovariate(rate), self._on_reclaim_tick
        )

    def _on_reclaim_tick(self) -> None:
        spot_ids = sorted(
            iid
            for iid, inst in self.instances.items()
            if inst.spot and not inst.draining
        )
        # Reclaim only while a survivor exists: a platform can take the
        # whole fleet, but the study's question is survival, not
        # annihilation.
        if spot_ids and len(self._routable()) > 1:
            iid = spot_ids[self._rng_reclaim.randrange(len(spot_ids))]
            self._begin_reclaim(self.instances[iid])
        if self._fleet_busy():
            self._schedule_next_reclaim()

    def _gen_progress(self, seq: _SimSeq) -> int:
        """Tokens this round has produced by now — the same elapsed/itl
        banking rule as :meth:`_preempt`."""
        gen = seq.gen_round
        if (
            seq.state is SeqState.ACTIVE
            and not seq.stalled
            and not seq.swapped
            and seq.itl > 0
        ):
            gen = min(
                max(
                    int((self.loop.now - seq.decode_start) / seq.itl),
                    seq.gen_round,
                ),
                seq.round_budget,
            )
        return gen if seq.state is not SeqState.WAITING else 0

    def _detach(self, seq: _SimSeq) -> int:
        """Remove the sequence from its instance, banking decode
        progress into its continuation prompt (delivered tokens are
        final — the journal guarantees no loss, no duplication).
        Returns tokens banked."""
        inst = seq.instance
        gen = self._gen_progress(seq)
        if seq.swapped:
            seq.swapped = False
            inst.host_free += seq.swap_pages
            seq.swap_pages = 0
            if seq in inst.swap_queue:
                inst.swap_queue.remove(seq)
        seq.epoch += 1
        seq.delivered += gen
        seq.prompt_len += gen
        seq.remaining -= gen
        if seq in inst.bound:
            inst.pages_free += seq.pages - seq.shared_page_count
            self._release_shared(inst, seq)
            seq.pages = 0
            inst.bound.remove(seq)
        else:
            self._remove_waiting(inst, seq)
        if seq.stalled:
            seq.stalled = False
            inst.stall_queue.remove(seq)
        if seq.decode_began:
            self._anatomy["decode_compute"] += self.loop.now - seq.decode_began
            seq.decode_began = 0.0
        if not seq.preempted_at:
            seq.preempted_at = self.loop.now  # limbo until re-admission
        seq.state = SeqState.WAITING
        seq.instance = None
        seq.cached_tokens = 0
        seq.migrated = False
        seq.g3_restore_s = 0.0
        return gen

    def _least_loaded(self) -> "_SimInstance | None":
        ready = self._routable()
        if not ready:
            return None
        return min(ready, key=lambda i: (len(i.bound) + len(i.waiting), i.id))

    def _requeue_on(self, seq: _SimSeq, dest: _SimInstance) -> None:
        seq.instance = dest
        dest.waiting.append(seq)
        self._pump(dest)

    def _failover(self, seq: _SimSeq) -> None:
        """Journal failover: the continuation re-prefills its whole
        context on the least-loaded survivor (queue-depth routing, the
        recovery router's behavior)."""
        self._detach(seq)
        self.report.reclaim_failovers += 1
        dest = self._least_loaded()
        if dest is None:
            self._log("req %d reclaim failover found no survivor", seq.req.index)
            self._finish(seq, "error")
            return
        self._log(
            "req %d failover -> inst %d (%d tok banked)",
            seq.req.index, dest.id, seq.delivered,
        )
        self._requeue_on(seq, dest)

    def _begin_reclaim(self, inst: _SimInstance) -> None:
        """A reclaim notice landed: flip the instance out of routing
        (the live metadata republish) and run the REAL triage planner
        over its in-flight work."""
        if inst.draining:
            return
        inst.draining = True
        cfg = self.cfg
        grace = cfg.reclaim_grace_s
        self.report.reclaims += 1
        survivors = [
            SurvivorInfo(
                instance=f"sim-{i.id}", instance_id=i.id, topology=i.topo
            )
            for i in self.instances.values()
            if i is not inst and not i.draining
        ]
        snaps: list[SequenceSnapshot] = []
        by_rid: dict[str, _SimSeq] = {}
        ps = cfg.page_size
        for seq in list(inst.bound):
            gen = self._gen_progress(seq)
            # Live-engine bound: only complete pages of confirmed
            # tokens ship; swapped rows' KV is not device-resident.
            full = (
                max(0, (seq.prompt_len + gen - 1) // ps)
                if seq.state is SeqState.ACTIVE and not seq.swapped
                else 0
            )
            snap = SequenceSnapshot(
                request_id=str(seq.req.index),
                priority=seq.priority,
                full_pages=full,
                kv_bytes=full * cfg.kv_bytes_per_page,
                tokens_generated=seq.delivered + gen,
            )
            snaps.append(snap)
            by_rid[snap.request_id] = seq
        plan = plan_triage(
            snaps,
            survivors,
            grace,
            origin=f"sim-{inst.id}",
            origin_topo=inst.topo,
            margin_s=cfg.reclaim_margin_s,
            est_fn=lambda _s, _d, nb: nb / cfg.migration_bw_bps,
        )
        n_mig = sum(1 for d in plan if d.action == MIGRATE)
        self._log(
            "reclaim notice inst %d (grace %.2fs): %d migrate, %d failover",
            inst.id, grace, n_mig, len(plan) - n_mig,
        )
        for d in plan:
            seq = by_rid[d.seq.request_id]
            if d.action == MIGRATE:
                self._detach(seq)
                self.report.reclaim_migrated += 1
                self.report.reclaim_migrated_pages += d.seq.full_pages
                self._log(
                    "req %d migrate inst %d -> inst %d (%d pages, eta %.3fs)",
                    seq.req.index, inst.id, d.dest.instance_id,
                    d.seq.full_pages, d.eta_s,
                )
                self.loop.after(
                    d.eta_s,
                    self._on_migrate_landed,
                    seq,
                    d.dest.instance_id,
                    d.seq.full_pages,
                    seq.epoch,
                )
            else:
                self._failover(seq)
        for seq in list(inst.waiting):
            # Never started here: plain reroute, nothing to ship.
            self._failover(seq)
        self.loop.after(grace, self._on_reclaim_kill, inst.id)

    def _on_migrate_landed(
        self, seq: _SimSeq, dest_id: int, full_pages: int, epoch: int
    ) -> None:
        if seq.epoch != epoch or seq.state is not SeqState.WAITING:
            return
        dest = self.instances.get(dest_id)
        if dest is None or dest.draining:
            # The survivor itself died mid-transfer: the journal still
            # owns correctness — plain failover, cache credit lost.
            self.report.reclaim_migrated -= 1
            self.report.reclaim_migrated_pages -= full_pages
            self.report.reclaim_failovers += 1
            self._log(
                "req %d migration target inst %d gone; journal failover",
                seq.req.index, dest_id,
            )
            fallback = self._least_loaded()
            if fallback is None:
                self._finish(seq, "error")
                return
            self._requeue_on(seq, fallback)
            return
        # The shipped prefix parked in dest's cache: the continuation
        # admits with that many tokens of prefill credit.
        seq.migrated = True
        seq.cached_tokens = min(
            full_pages * self.cfg.page_size, seq.prompt_len - 1
        )
        self._log(
            "req %d migration landed on inst %d (%d tok cached)",
            seq.req.index, dest.id, seq.cached_tokens,
        )
        self._requeue_on(seq, dest)

    def _on_reclaim_kill(self, iid: int) -> None:
        inst = self.instances.get(iid)
        if inst is None:
            return
        # Triage displaced everything at the notice; anything that
        # landed since (it can't — the instance left routing) or was
        # missed degrades to failover rather than dying with the host.
        for seq in list(inst.bound) + list(inst.waiting):
            self._failover(seq)
        was_spot = inst.spot
        self._retire(inst)
        self._log("instance %d reclaimed", iid)
        if was_spot and self._fleet_busy():
            # The spot pool refills: same capacity class, fresh host.
            self._account_chips()
            self._provisioning += 1
            self._provisioning_spot += 1
            delay = (
                self.cfg.provision_s
                if self.cfg.provision_s is not None
                else self.cfg.service.provision_s
            )
            self.loop.after(delay, self._on_spot_ready)
            self._log("instance provisioning (spot respawn)")

    def _on_spot_ready(self) -> None:
        self._account_chips()
        self._provisioning -= 1
        self._provisioning_spot -= 1
        self._spawn_ready(spot=True)

    # ------------------------------------------------------ restart drill
    def _start_restart_drill(self) -> None:
        if self.cfg.restart_at_s is not None:
            self.loop.after(self.cfg.restart_at_s, self._on_restart)

    def _on_restart(self) -> None:
        """Hard restart drill: the busiest instance dies with NO grace
        (power cut, not a reclaim notice) — in-flight work journals
        over to survivors, the host respawns after provision_s on the
        SAME modeled disk, and its G3 store re-adopts (the live
        boot_scan), so returning prefix groups re-attach warm."""
        live = self._routable()
        if not live:
            return
        inst = max(live, key=lambda i: (len(i.bound) + len(i.waiting), i.id))
        self.report.restarts += 1
        self._log(
            "instance %d hard restart (%d g3 pages survive)",
            inst.id, len(inst.g3),
        )
        inst.draining = True  # out of routing before failovers reroute
        g3 = inst.g3
        for seq in list(inst.bound) + list(inst.waiting):
            self._failover(seq)
        if inst.id in self.instances:  # _finish may have retired it
            self._retire(inst)
        self._account_chips()
        self._provisioning += 1
        delay = (
            self.cfg.provision_s
            if self.cfg.provision_s is not None
            else self.cfg.service.provision_s
        )
        self.loop.after(delay, self._on_restart_ready, g3)

    def _on_restart_ready(self, g3: dict) -> None:
        self._account_chips()
        self._provisioning -= 1
        inst = self._spawn_ready()
        inst.g3 = g3
        self._log(
            "instance %d restarted, adopted %d g3 pages", inst.id, len(g3)
        )

    # ------------------------------------------------------------- planner
    def _start_planner(self) -> None:
        if self.cfg.planner is None:
            return
        self.loop.after(
            self._pcfg.metric_pulling_interval, self._on_metric_tick
        )
        self.loop.after(
            self._pcfg.adjustment_interval, self._on_adjust_tick
        )

    def _fleet_busy(self) -> bool:
        return not self._stream_done or self._open > 0

    def _on_metric_tick(self) -> None:
        """Mirror Planner.collect_metrics: one KV sample per instance
        per scrape, biased up by waiting work about to claim cache."""
        for inst in self.instances.values():
            m = inst.refresh_metrics()
            kv = m.gpu_cache_usage_perc
            if m.request_active_slots and m.num_requests_waiting > 0:
                kv += (
                    self._pcfg.waiting_request_kv_estimate
                    * m.num_requests_waiting
                )
            self._kv_samples.append(kv)
        if self._fleet_busy():
            self.loop.after(
                self._pcfg.metric_pulling_interval, self._on_metric_tick
            )

    def _on_adjust_tick(self) -> None:
        # Pressure inputs from the shared attribution window — the same
        # window_percentiles()/reset_window() round the live Planner
        # makes against the HTTP edge's attribution.
        ttft_p99, itl_p99 = self.slo_attr.window_percentiles()
        obs = PlannerObservation(
            num_prefill=0,
            num_decode=len(self.instances) + self._provisioning,
            prefill_queue=(),
            kv_load=tuple(self._kv_samples),
            ttft_p99_s=ttft_p99,
            itl_p99_s=itl_p99,
            now=self.loop.now,
        )
        if self.cfg.planner == "slo":
            decision, self._plan_state = plan_step_slo(
                obs, self._plan_state, self._pcfg, self._slo
            )
        else:
            decision, self._plan_state = plan_step(
                obs, self._plan_state, self._pcfg
            )
        for action in decision.actions:
            entry = action.as_log() | {"t": round(self.loop.now, 3)}
            self.report.planner_actions.append(entry)
            self._log("planner %s (signal %.3f)", action.op, action.signal)
            if action.op == "add":
                self._provision()
                if decision.arm_decode_grace:
                    # Provisioning always lands in sim, so every
                    # proposed add earns its grace period.
                    self._plan_state = arm_decode_grace(self._plan_state)
            else:
                ready = [
                    i for i in self.instances.values() if not i.draining
                ]
                if len(ready) > self._pcfg.min_endpoint:
                    inst = max(ready, key=lambda i: i.id)  # youngest
                    inst.draining = True
                    if inst.idle and len(self.instances) > 1:
                        self._retire(inst)
        self._kv_samples = []
        self.slo_attr.reset_window()
        if self._fleet_busy():
            self.loop.after(
                self._pcfg.adjustment_interval, self._on_adjust_tick
            )

    # ----------------------------------------------------------------- run
    def run(self) -> SimReport:
        t0 = time.perf_counter()  # dynlint: determinism(host-only wall-clock report field)
        self._chips_since = self.loop.now
        self._schedule_next_arrival()
        self._start_planner()
        self._start_reclaims()
        self._start_restart_drill()
        self.loop.run(max_events=self.cfg.max_events)
        self._account_chips()
        r = self.report
        if self._open > 0:
            # Requests stranded with no event left to free them (every
            # row stalled at its preemption bound): the live analogue is
            # a hang, which the engine's capacity fixes make unreachable
            # in practice — surface it as errors, never silently.
            self._log("%d requests starved at drain", self._open)
            r.errors += self._open
        r.duration_s = self.loop.now
        r.events = self.loop.processed
        r.accepted_per_dispatch = round(
            max(self.cfg.service.spec_tokens_per_dispatch, 1.0), 4
        )
        r.wall_clock_s = round(time.perf_counter() - t0, 3)  # dynlint: determinism(host-only wall-clock report field)
        r.chip_seconds = round(self._chip_seconds, 3)
        r.billed_chip_seconds = round(self._billed_chip_seconds, 3)
        if r.duration_s > 0:
            r.goodput_tok_s = round(r.completed_tokens / r.duration_s, 3)
        # SLO attribution totals (shared telemetry/slo.py code path —
        # the live edge's dynamo_goodput_requests_total /
        # dynamo_slo_violations_total equivalents).
        r.goodput_requests = self.slo_attr.goodput_total
        # Latency anatomy rollup (same component names as the live
        # telemetry/anatomy.py plane, restricted to what the sim models).
        r.anatomy = {k: round(v, 6) for k, v in self._anatomy.items()}
        r.slo_violations_ttft = self.slo_attr.violations["ttft"]
        r.slo_violations_itl = self.slo_attr.violations["itl"]
        r.ttft_p50_s = percentile(self._ttfts, 0.5)
        r.ttft_p99_s = percentile(self._ttfts, 0.99)
        r.itl_p50_s = percentile(self._itls, 0.5)
        r.itl_p99_s = percentile(self._itls, 0.99)
        # Fleet rollup through the SAME FleetView code path the live
        # FleetAggregator uses (docs/observability.md "Fleet plane"), so
        # fleet numbers are comparable live<->sim by construction. Keyed
        # sim-<id> in sorted order; rollup is deterministic (the view's
        # wall-clock scrape stamp never enters it).
        from ..telemetry.fleet import FleetView

        r.fleet = FleetView.from_snapshots(
            {
                f"sim-{iid}": {
                    **inst.refresh_metrics().to_dict(),
                    "preemptions": inst.preemptions,
                    "draining": inst.draining,
                }
                for iid, inst in self.instances.items()
            }
        ).rollup()
        return r
