"""Pytest root conftest: force JAX onto an 8-device virtual CPU mesh.

Tests never require real TPU hardware; multi-chip sharding is validated on
virtual CPU devices (the driver separately dry-runs the multichip path).
Must run before jax initializes its backends, hence env vars here.
"""

import asyncio
import inspect
import os

import pytest

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Subprocesses the tests spawn (proc workers, SDK supervisors) must not
# register accelerator PJRT plugins: the image's sitecustomize (on
# PYTHONPATH) dials a remote TPU tunnel at interpreter startup, which
# can block a pure-CPU child indefinitely when the tunnel is busy.
os.environ["PYTHONPATH"] = ":".join(
    p for p in os.environ.get("PYTHONPATH", "").split(":") if p and "axon" not in p
)

# The image's sitecustomize registers the TPU-tunnel backend and makes it
# the default regardless of env vars; override at the config level too so
# the test suite deterministically runs on the virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "asyncio: run the coroutine test on a fresh event loop"
    )


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Minimal asyncio support (pytest-asyncio is not in the image)."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(asyncio.wait_for(fn(**kwargs), timeout=120))
        return True
    return None
