"""Pure planner decision logic: ``plan_step`` and friends.

The live :class:`~dynamo_exp_tpu.planner.planner.Planner` loop and the
cluster simulator (``dynamo_exp_tpu/sim/``) share ONE implementation of
the scaling policy. Everything here is a pure function of an
observation and a state — no asyncio, no coordinator, no wall clock —
so a scaling decision is unit-testable in microseconds and a simulated
fleet of millions of users exercises exactly the code production runs.

Two policies:

- :func:`plan_step` — the reference's reactive threshold loop
  (``/root/reference/examples/llm/components/planner.py:225-305``),
  ported verbatim from the previous in-loop implementation: scale-down
  checks before scale-up, decode grace period after an add, prefill
  scale-up gated on the queue trend staying above threshold, hard chip
  budget.
- :func:`plan_step_slo` — SLO-driven predictive scaling: forecasts
  per-worker KV load and queue depth along their observed linear trends
  and sizes the fleet to keep p99 TTFT / ITL under explicit targets
  instead of reacting to raw thresholds after they're breached. Can add
  (and remove) more than one worker per round, bounded by
  ``max_scale_step`` and the chip budget.

The decision (:class:`Decision`) is a plan, not an effect: the caller —
live loop or simulator — applies each :class:`ScaleAction` through its
connector and folds what actually happened back into state: when a
proposed decode add lands, the caller applies :func:`arm_decode_grace`
(arming the scale-down grace period for a worker that never spawned
would pin an overscaled fleet for the whole grace window). Budget
accounting inside a step assumes the proposed actions succeed; a
connector failure merely wastes a round (the next observation window
re-derives the fleet from discovery).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from ..telemetry.fingerprint import (
    DRIFT_ALERT_THRESHOLD,
    WorkloadFingerprint,
    drift_score,
)

# Number of adjustment intervals a new decode worker is protected from
# scale-down (reference: planner.py:42).
NEW_DECODE_WORKER_GRACE_PERIOD = 3
# Prefill scale-up looks this many intervals ahead along the queue's
# observed trend (reference: planner.py:48).
NEW_PREFILL_WORKER_QUEUE_BUFFER_PERIOD = 3


@dataclass(frozen=True)
class PlannerObservation:
    """One adjustment interval's worth of signals.

    ``prefill_queue`` / ``kv_load`` are the raw per-scrape samples (the
    live loop collects one per metric-pulling interval per worker); an
    empty tuple is NO signal, not zero load — a scrape outage must
    never read as idle. The SLO fields are optional percentile
    measurements over the interval's completions; ``None`` means not
    measured (the reactive policy ignores them entirely)."""

    num_prefill: int
    num_decode: int
    prefill_queue: tuple[float, ...] = ()
    kv_load: tuple[float, ...] = ()
    ttft_p99_s: float | None = None
    itl_p99_s: float | None = None
    now: float = 0.0
    # Workload-fingerprint plane (PR 16): drift of live traffic vs the
    # pinned reference, and the live fingerprint itself. ``None`` means
    # the fingerprint plane isn't wired — the catalog swap stays off.
    drift_score: float | None = None
    fingerprint: WorkloadFingerprint | None = None


@dataclass(frozen=True)
class PlannerState:
    """Cross-interval memory. Today that is only the decode grace
    counter; keeping it a dataclass makes the fold explicit and lets
    the simulator snapshot/replay planner state."""

    decode_grace_remaining: int = 0
    # Name of the config-catalog entry currently in force ("" = the
    # deployment default). Folded by the catalog swap in
    # :func:`plan_step_slo`.
    active_config: str = ""


@dataclass(frozen=True)
class CatalogEntry:
    """One pre-validated tuned config the planner may swap to when
    live traffic drifts (``llmctl tune`` emits these; docs/tuning.md).
    ``overrides`` is a tuple of ``(knob, value)`` pairs — hashable, so
    the entry stays frozen; ``config_hash`` is the tune artifact's
    stable knob hash (the same one bench lines are stamped with)."""

    name: str
    fingerprint: WorkloadFingerprint
    overrides: tuple = ()
    config_hash: str = ""


@dataclass(frozen=True)
class ScaleAction:
    op: str  # "add" | "remove"
    component: str
    signal: float  # the metric value that triggered the action

    def as_log(self) -> dict:
        return {
            "op": self.op,
            "component": self.component,
            "signal": round(self.signal, 4),
        }


@dataclass(frozen=True)
class Decision:
    actions: tuple[ScaleAction, ...] = ()
    # Human-readable skipped/considered notes (grace period, drain
    # prediction, budget caps) for observability and test assertions.
    notes: tuple[str, ...] = ()
    # A decode scale-up is proposed: the caller must fold
    # :func:`arm_decode_grace` into its state IF (and only if) the add
    # actually lands — arming on a failed add would protect a worker
    # that never existed from scale-down for the whole grace period.
    arm_decode_grace: bool = False
    # Catalog swap proposed this round (``plan_step_slo`` only):
    # {"name", "config_hash", "drift_before", "drift_after",
    # "overrides"}. The caller records the flight/trace event and bumps
    # ``dynamo_config_swaps_total``; the new active entry is already
    # folded into the returned PlannerState.
    config_swap: dict | None = None


def arm_decode_grace(state: PlannerState) -> PlannerState:
    """Fold a *successful* decode scale-up into planner state: the new
    worker is protected from scale-down for the grace period. The value
    is post-decrement — the arming round itself already counts (the
    reference sets 3 then decrements on the way out)."""
    return PlannerState(
        decode_grace_remaining=max(
            state.decode_grace_remaining, NEW_DECODE_WORKER_GRACE_PERIOD - 1
        ),
        active_config=state.active_config,
    )


def maybe_swap_config(
    obs: PlannerObservation, state: PlannerState, cfg
) -> tuple[dict | None, str, list[str]]:
    """The catalog-swap decision: when live drift vs the pinned
    reference crosses :data:`DRIFT_ALERT_THRESHOLD` (the same number
    the fleet doctor flags DRIFT on), pick the catalog entry whose
    fingerprint is nearest the live one — and swap only if it is
    strictly nearer than the current drift (swapping to an equally
    wrong config would just churn). Pure: returns (swap-or-None,
    new-active-name, notes)."""
    catalog = tuple(getattr(cfg, "config_catalog", ()) or ())
    if (
        not catalog
        or obs.fingerprint is None
        or obs.drift_score is None
        or obs.drift_score < DRIFT_ALERT_THRESHOLD
    ):
        return None, state.active_config, []
    scored = sorted(
        (drift_score(obs.fingerprint, e.fingerprint), e.name, e)
        for e in catalog
    )
    best_d, _, best = scored[0]
    if best.name == state.active_config:
        return (
            None,
            state.active_config,
            [f"drift {obs.drift_score:.2f} but {best.name!r} already active"],
        )
    if best_d >= obs.drift_score:
        return (
            None,
            state.active_config,
            [
                f"drift {obs.drift_score:.2f}: no catalog entry nearer "
                f"(best {best.name!r} at {best_d:.2f})"
            ],
        )
    swap = {
        "name": best.name,
        "config_hash": best.config_hash,
        "drift_before": obs.drift_score,
        "drift_after": best_d,
        "overrides": dict(best.overrides),
    }
    return swap, best.name, []


def _mean(samples: tuple[float, ...]) -> float | None:
    return sum(samples) / len(samples) if samples else None


def _trend_forecast(samples: tuple[float, ...], horizon: float) -> float:
    """Last sample extrapolated ``horizon`` windows along the linear
    trend observed across the sample window (the same first-to-last
    slope the reference's prefill gate uses)."""
    if not samples:
        return 0.0
    trend = samples[-1] - samples[0] if len(samples) >= 2 else 0.0
    return samples[-1] + trend * horizon


def plan_step(
    obs: PlannerObservation, state: PlannerState, cfg
) -> tuple[Decision, PlannerState]:
    """The reactive threshold policy. ``cfg`` is a
    :class:`~dynamo_exp_tpu.planner.planner.PlannerConfig` (duck-typed:
    only the threshold/budget fields are read)."""
    actions: list[ScaleAction] = []
    notes: list[str] = []
    grace = state.decode_grace_remaining
    curr_chips = (
        obs.num_prefill * cfg.prefill_engine_num_tpu
        + obs.num_decode * cfg.decode_engine_num_tpu
    )
    avg_queue = _mean(obs.prefill_queue)
    avg_kv = _mean(obs.kv_load)

    # -- scale down first (reference ordering, planner.py:225-252)
    if (
        obs.num_prefill
        and avg_queue is not None
        and avg_queue < cfg.prefill_queue_scale_down_threshold
        and obs.num_prefill > cfg.min_endpoint
    ):
        actions.append(ScaleAction("remove", cfg.prefill_component, avg_queue))
        curr_chips -= cfg.prefill_engine_num_tpu
    if (
        avg_kv is not None
        and avg_kv < cfg.decode_kv_scale_down_threshold
        and obs.num_decode > cfg.min_endpoint
    ):
        if grace > 0:
            notes.append(f"decode scale-down skipped (grace period {grace})")
        else:
            actions.append(
                ScaleAction("remove", cfg.decode_component, avg_kv)
            )
            curr_chips -= cfg.decode_engine_num_tpu

    # -- scale up (prefill first: its queueing also inflates decode KV)
    if (
        obs.num_prefill
        and avg_queue is not None
        and avg_queue > cfg.prefill_queue_scale_up_threshold
        and curr_chips + cfg.prefill_engine_num_tpu <= cfg.max_tpu_budget
    ):
        predicted = _trend_forecast(
            obs.prefill_queue, NEW_PREFILL_WORKER_QUEUE_BUFFER_PERIOD
        )
        if predicted > cfg.prefill_queue_scale_up_threshold:
            actions.append(
                ScaleAction("add", cfg.prefill_component, avg_queue)
            )
            curr_chips += cfg.prefill_engine_num_tpu
        else:
            notes.append(
                f"prefill queue trend predicts drain ({predicted:.2f}); "
                "not scaling"
            )
    arm = False
    if (
        avg_kv is not None
        and avg_kv > cfg.decode_kv_scale_up_threshold
        and curr_chips + cfg.decode_engine_num_tpu <= cfg.max_tpu_budget
    ):
        actions.append(ScaleAction("add", cfg.decode_component, avg_kv))
        curr_chips += cfg.decode_engine_num_tpu
        arm = True

    if grace > 0:
        grace -= 1
    return (
        Decision(tuple(actions), tuple(notes), arm_decode_grace=arm),
        PlannerState(grace, active_config=state.active_config),
    )


# --------------------------------------------------------------------- SLO
@dataclass
class SloTargets:
    """SLO-driven predictive knobs, layered over a PlannerConfig.

    ``provision_s`` is a fitted-service hint from telemetry (the
    simulator's
    :meth:`~dynamo_exp_tpu.sim.fit.ServiceTimeModel.planner_hints`);
    zero means unknown and disables the corresponding estimate."""

    ttft_p99_slo_s: float = 2.0
    itl_p99_slo_s: float = 0.2
    # Windows of look-ahead along the observed trend (in adjustment
    # intervals): the whole point of "predictive" — scale for where the
    # signal is going, not where it is.
    forecast_horizon: float = 2.0
    # Per-worker KV load the fleet is sized to sit at. Well below the
    # reactive 0.9 threshold: past ~0.85 the engine starts stalling and
    # preempting, which is exactly what blows up p99 ITL.
    decode_kv_target: float = 0.75
    # Queue depth per prefill worker the fleet is sized to sit at.
    prefill_queue_target: float = 2.0
    # Most workers added or removed in one adjustment round.
    max_scale_step: int = 4
    # Desired/current below this fraction → remove one worker (deep
    # hysteresis so the fleet doesn't flap around the target).
    scale_down_headroom: float = 0.6
    # A single observed-pressure ratio is trusted at most this far (a
    # p99 blown 10x should not 10x the fleet in one round).
    max_pressure: float = 3.0
    # Measured worker add -> serving delay. A scale-up decided now only
    # lands this far in the future, so the forecast looks that much
    # further along the trend (in addition to ``forecast_horizon``).
    # 0 = unknown: no extension. Fitted from tagged coldstart bench
    # lines (``bench.py --coldstart-sweep`` →
    # ``ServiceTimeModel.planner_hints()``): a warm-booting fleet
    # (docs/aot.md) plans with its measured warm landing delay — the
    # whole chip-seconds win of AOT prewarm enters the policy through
    # this one number (shorter horizon → scale on the burst edge
    # instead of buying standby capacity ahead of it).
    provision_s: float = 0.0


def plan_step_slo(
    obs: PlannerObservation,
    state: PlannerState,
    cfg,
    slo: SloTargets,
) -> tuple[Decision, PlannerState]:
    """SLO-driven predictive scaling.

    Sizing logic (decode / aggregated fleet):

    1. Forecast per-worker KV load ``forecast_horizon`` windows ahead
       along its linear trend. ``kv_pressure = forecast / kv_target``.
    2. Measure SLO attainment directly when available:
       ``ttft_pressure = ttft_p99 / ttft_slo`` and likewise for ITL —
       a breached target demands capacity even when KV looks fine
       (e.g. queue-bound TTFT), clamped to ``max_pressure``.
    3. ``desired = ceil(current * max(pressures))``, bounded by
       ``max_scale_step``, the chip budget, and ``min_endpoint``.
    4. Scale down (one worker, grace-gated) only when every pressure
       forecast sits below ``scale_down_headroom``.

    The prefill fleet (disaggregated mode) is sized the same way from
    the queue-depth forecast against ``prefill_queue_target``.
    """
    actions: list[ScaleAction] = []
    notes: list[str] = []
    grace = state.decode_grace_remaining
    chips = (
        obs.num_prefill * cfg.prefill_engine_num_tpu
        + obs.num_decode * cfg.decode_engine_num_tpu
    )

    # --------------------------------------------------- catalog swap
    swap, active, swap_notes = maybe_swap_config(obs, state, cfg)
    notes.extend(swap_notes)

    def clamp_pressure(x: float) -> float:
        return min(max(x, 0.0), slo.max_pressure)

    arm = False

    # Scale-ups decided now land provision_s later; look that much
    # further along the trend (in adjustment-interval windows).
    horizon = slo.forecast_horizon
    if slo.provision_s > 0:
        horizon += slo.provision_s / max(cfg.adjustment_interval, 1e-9)

    # ------------------------------------------------------------- decode
    kv_forecast = (
        _trend_forecast(obs.kv_load, horizon) if obs.kv_load else 0.0
    )
    pressures = []
    if obs.kv_load:
        pressures.append(clamp_pressure(kv_forecast / slo.decode_kv_target))
    if obs.ttft_p99_s is not None and slo.ttft_p99_slo_s > 0:
        pressures.append(
            clamp_pressure(obs.ttft_p99_s / slo.ttft_p99_slo_s)
        )
    if obs.itl_p99_s is not None and slo.itl_p99_slo_s > 0:
        pressures.append(clamp_pressure(obs.itl_p99_s / slo.itl_p99_slo_s))

    if pressures and obs.num_decode > 0:
        pressure = max(pressures)
        desired = max(
            math.ceil(obs.num_decode * pressure), cfg.min_endpoint
        )
        if desired > obs.num_decode:
            add = min(desired - obs.num_decode, slo.max_scale_step)
            # Chip budget caps the expansion.
            afford = (cfg.max_tpu_budget - chips) // max(
                cfg.decode_engine_num_tpu, 1
            )
            if add > afford:
                notes.append(
                    f"decode scale-up capped by budget ({add} -> {afford})"
                )
                add = afford
            signal = obs.kv_load[-1] if obs.kv_load else pressure
            for _ in range(max(add, 0)):
                actions.append(
                    ScaleAction("add", cfg.decode_component, signal)
                )
                chips += cfg.decode_engine_num_tpu
            if add > 0:
                arm = True
        elif (
            pressure < slo.scale_down_headroom
            and obs.num_decode > cfg.min_endpoint
        ):
            if grace > 0:
                notes.append(
                    f"decode scale-down skipped (grace period {grace})"
                )
            else:
                actions.append(
                    ScaleAction(
                        "remove", cfg.decode_component, kv_forecast
                    )
                )
                chips -= cfg.decode_engine_num_tpu

    # ------------------------------------------------------------ prefill
    if obs.num_prefill and obs.prefill_queue:
        q_forecast = max(_trend_forecast(obs.prefill_queue, horizon), 0.0)
        per_worker = q_forecast / obs.num_prefill
        p_pressure = clamp_pressure(per_worker / slo.prefill_queue_target)
        desired = max(
            math.ceil(obs.num_prefill * p_pressure), cfg.min_endpoint
        )
        if desired > obs.num_prefill:
            add = min(desired - obs.num_prefill, slo.max_scale_step)
            afford = (cfg.max_tpu_budget - chips) // max(
                cfg.prefill_engine_num_tpu, 1
            )
            add = min(add, max(afford, 0))
            for _ in range(add):
                actions.append(
                    ScaleAction(
                        "add", cfg.prefill_component, obs.prefill_queue[-1]
                    )
                )
                chips += cfg.prefill_engine_num_tpu
        elif (
            p_pressure < slo.scale_down_headroom
            and obs.num_prefill > cfg.min_endpoint
        ):
            actions.append(
                ScaleAction("remove", cfg.prefill_component, q_forecast)
            )
            chips -= cfg.prefill_engine_num_tpu

    if grace > 0:
        grace -= 1
    return (
        Decision(
            tuple(actions),
            tuple(notes),
            arm_decode_grace=arm,
            config_swap=swap,
        ),
        PlannerState(grace, active_config=active),
    )
