"""LocalModel attach: publish a model so ingress can discover and serve it.

Capability parity with the reference's ``LocalModel::attach`` +
``register_llm`` flow (``/root/reference/lib/llm/src/local_model.rs:1-164``,
``lib/bindings/python/rust/lib.rs:104-131``, ``http/service/discovery.rs:50-80``):
the worker publishes its ModelDeploymentCard to the object store (bucket
``mdc``) and writes a lease-scoped ModelEntry into the discovery KV under
``models/``; frontends watch that prefix, fetch the card, and build the
preprocessor→backend→router chain. Worker death revokes the lease, the
entry disappears, and the frontend drops the model — elastic membership.

Note: the card's ``tokenizer_path`` is a filesystem path, so frontends
must share a filesystem (or model cache) with workers — the TPU-pod
deployment story, where every host has the model directory.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass

from .model_card import ModelDeploymentCard
from .runtime.component import DistributedRuntime, Endpoint

MDC_BUCKET = "mdc"
MODELS_PREFIX = "models/"


@dataclass
class ModelEntry:
    """What ingress needs to route to a served model."""

    name: str
    endpoint: str  # dyn://namespace.component.endpoint
    model_type: str = "both"  # "chat" | "completion" | "both"
    mdc_key: str = ""  # object-store key of the ModelDeploymentCard

    def to_bytes(self) -> bytes:
        return json.dumps(asdict(self)).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "ModelEntry":
        return cls(**json.loads(raw))


async def register_llm(
    drt: DistributedRuntime,
    endpoint: Endpoint,
    model_path: str,
    model_name: str | None = None,
    model_type: str = "both",
    kv_cache_block_size: int | None = None,
) -> ModelEntry:
    """Publish MDC + ModelEntry so frontends can discover this worker's
    model. The entry rides the process's primary lease: if this worker
    dies, ingress unregisters the model automatically."""
    mdc = ModelDeploymentCard.from_local_path(model_path, model_name)
    if kv_cache_block_size:
        mdc.kv_cache_block_size = kv_cache_block_size
    await drt.object_store.put(MDC_BUCKET, mdc.slug, mdc.to_json().encode())
    entry = ModelEntry(
        name=mdc.display_name,
        endpoint=f"dyn://{endpoint.address.subject}",
        model_type=model_type,
        mdc_key=mdc.slug,
    )
    lease = await drt.primary_lease()
    # Keyed per worker (lease id suffix): N replicas write N entries, and
    # one replica's death removes only its own — the model stays served
    # until the last replica is gone (reference keys entries per instance).
    key = f"{MODELS_PREFIX}{mdc.slug}/{lease.lease_id}"
    await drt.discovery.kv_put(key, entry.to_bytes(), lease)
    return entry
