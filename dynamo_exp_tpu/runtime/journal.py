"""Replay journal for resumable generation (docs/fault_tolerance.md
"Resumable streams").

The frontend/router layer keeps, per in-flight request, everything
needed to rebuild the generation elsewhere: the prompt token ids, the
sampling parameters **with the RNG seed pinned** (the engine's sampler
is counter-based — every draw is keyed by (seed, absolute token
position) — so a pinned seed makes the whole stream a pure function of
the request), and every emitted completion token with its sequence
index. When the stream breaks mid-decode, the router re-dispatches a
**continuation request**: prompt + journaled tokens as the new
``token_ids`` (one batched re-prefill on the surviving worker), the
token budget reduced by what was already delivered, and
``resume_offset`` marking the journaled tail. Greedy continuations are
token-identical to an uninterrupted run; sampled continuations replay
the journaled seed deterministically.

The journal also deduplicates by sequence index on the way out: a frame
whose tokens land at already-journaled indices is trimmed (counted on
``dynamo_tokens_deduplicated_total``), so the client-facing stream is
gap-free and duplicate-free no matter how the failover interleaved.
"""

from __future__ import annotations

import random
from typing import Any

from ..telemetry import get_telemetry


class ReplayJournal:
    """Per-request token journal + continuation builder."""

    def __init__(self, request: dict, prompt: list[int]):
        # The seed-pinned request actually dispatched (and the base of
        # every continuation).
        self.request = request
        self.prompt = prompt
        # Journaled completion tokens; a token's sequence index IS its
        # list index.
        self.tokens: list[int] = []
        self.recoveries = 0
        self.finished = False
        # Current physical stream's emission cursor: the journal offset
        # where it began and how many tokens it has produced so far.
        self._stream_base = 0
        self._stream_pos = 0

    # ------------------------------------------------------------ build
    @classmethod
    def for_request(
        cls, request: Any, rng: random.Random
    ) -> "ReplayJournal | None":
        """A journal for ``request``, or None when the request is not
        journalable (not an engine-level dict with ``token_ids``).

        Sampled requests without an explicit seed get one pinned here —
        journaling the "RNG seed path" means choosing it at the frontend,
        where the continuation can repeat it verbatim."""
        if not isinstance(request, dict):
            return None
        token_ids = request.get("token_ids")
        if not isinstance(token_ids, list) or not token_ids:
            return None
        if any(not isinstance(t, int) for t in token_ids):
            return None
        req = dict(request)
        so = dict(req.get("sampling_options") or {})
        if (so.get("temperature") or 0.0) > 0.0 and so.get("seed") is None:
            so["seed"] = rng.getrandbits(31)
            req["sampling_options"] = so
        return cls(req, list(token_ids))

    # ----------------------------------------------------------- record
    @property
    def next_index(self) -> int:
        return len(self.tokens)

    def record(self, frame: dict) -> dict | None:
        """Journal one engine frame on its way to the caller.

        Returns the frame to emit (possibly trimmed of duplicate-index
        tokens, possibly usage-fixed), or None when nothing of it
        survives deduplication."""
        if not isinstance(frame, dict):
            return frame
        toks = frame.get("token_ids") or []
        if toks:
            start = self._stream_base + self._stream_pos
            self._stream_pos += len(toks)
            # Tokens at indices below the journal head were already
            # delivered by a previous incarnation of the stream.
            overlap = min(max(len(self.tokens) - start, 0), len(toks))
            fresh = toks[overlap:]
            self.tokens.extend(fresh)
            if overlap:
                get_telemetry().tokens_deduplicated.inc(overlap)
                if not fresh and not frame.get("finish_reason"):
                    return None
                frame = {**frame, "token_ids": fresh}
                # Per-token payloads stay index-aligned with token_ids;
                # pre-detokenized ``text`` (Backend-level frames) can't
                # be split by token and is dropped with the duplicates —
                # journaling is meant to sit *below* the detokenizer.
                for key in ("logprobs", "top_logprobs"):
                    if isinstance(frame.get(key), list):
                        frame[key] = frame[key][overlap:]
                frame.pop("text", None)
        if frame.get("finish_reason"):
            self.finished = True
            if self.recoveries:
                # A continuation's engine saw prompt+journal as prompt
                # and only its own tokens as completion; report the
                # client's view instead.
                frame = {**frame}
                if frame.get("prompt_tokens") is not None:
                    frame["prompt_tokens"] = len(self.prompt)
                if frame.get("completion_tokens") is not None:
                    frame["completion_tokens"] = len(self.tokens)
        return frame

    # ------------------------------------------------------ continuation
    def begin_continuation(self) -> None:
        """A replacement stream was dispatched: it emits from the
        journal head (its engine re-prefilled everything journaled)."""
        self._stream_base = len(self.tokens)
        self._stream_pos = 0

    def continuation_request(self) -> dict:
        """The re-dispatch payload: prompt + journaled tokens re-enter as
        ``token_ids`` (one batched prefill on the new worker), the token
        budget shrinks by what was delivered, and ``resume_offset``
        marks the journaled tail for telemetry/accounting."""
        req = dict(self.request)
        req["token_ids"] = self.prompt + self.tokens
        req["resume_offset"] = len(self.tokens)
        sc = dict(req.get("stop_conditions") or {})
        if sc.get("max_tokens") is not None:
            sc["max_tokens"] = max(sc["max_tokens"] - len(self.tokens), 1)
        if sc.get("min_tokens"):
            sc["min_tokens"] = max(sc["min_tokens"] - len(self.tokens), 0)
        req["stop_conditions"] = sc
        return req

    def synthetic_finish(self) -> dict | None:
        """When the stream died *between* the final token and its finish
        frame, the budget may already be spent — finishing locally beats
        re-prefilling the whole sequence to generate zero tokens."""
        sc = self.request.get("stop_conditions") or {}
        max_tokens = sc.get("max_tokens")
        if max_tokens is not None and len(self.tokens) >= max_tokens:
            self.finished = True
            return {
                "finish_reason": "length",
                "prompt_tokens": len(self.prompt),
                "completion_tokens": len(self.tokens),
            }
        return None
