"""determinism checker: no nondeterminism sources in seeded zones.

Two parts (docs/static_analysis.md "determinism"):

1. **Zone scan** — inside declared seed-deterministic zones (``sim/``,
   ``spec/``, the chaos schedules, ``FlightRecorder``), forbid:
   wall clocks (``time.time``/``monotonic``/``perf_counter``,
   ``datetime.now``...), module-level ``random.*`` draws (seeded
   ``random.Random(seed)`` instances are the sanctioned source),
   ``uuid.*``, ``os.urandom``, the unseeded ``np.random.*`` globals
   (``np.random.default_rng(seed)`` is fine), and ``id()``/``hash()``
   (``hash()`` of a str is salted per process — PYTHONHASHSEED).

2. **Payload-sink scan** — *everywhere* in the tree, arguments of
   ``*.flight.record(...)`` calls must be free of the same sources.
   This is the PR 8 gotcha as a rule: flight-ring payloads are
   compared bit-for-bit across same-seed runs, so a wall time or a
   run-global id in a payload breaks the chaos bit-identity test the
   day somebody adds one. The recorder stamps ``t`` itself; events
   carry pages/request/slot, never uuids.
"""

from __future__ import annotations

import ast

from .core import (
    Finding,
    ScopeIndex,
    Zone,
    attr_chain,
    dataflow_units,
    own_nodes,
    zone_for,
)

RULE = "determinism"

_TIME_FNS = {
    "time",
    "time_ns",
    "monotonic",
    "monotonic_ns",
    "perf_counter",
    "perf_counter_ns",
    "localtime",
    "gmtime",
    "strftime",
    "ctime",
}
_DATETIME_FNS = {"now", "utcnow", "today", "fromtimestamp"}
_RANDOM_FNS = {
    "random",
    "randint",
    "randrange",
    "randbytes",
    "choice",
    "choices",
    "shuffle",
    "sample",
    "uniform",
    "gauss",
    "normalvariate",
    "expovariate",
    "betavariate",
    "getrandbits",
    "seed",
}


class _ImportTable:
    """Resolves names through the file's imports so `from time import
    time` / `import time as tm` are as visible as `time.time`."""

    def __init__(self, tree: ast.Module):
        # local name -> full dotted path it stands for (as a tuple).
        self.aliases: dict[str, tuple[str, ...]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if node.module and not node.level:
                    mod = tuple(node.module.split("."))
                    for a in node.names:
                        self.aliases[a.asname or a.name] = mod + (a.name,)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    path = tuple(a.name.split("."))
                    if a.asname:
                        self.aliases[a.asname] = path
                    else:
                        self.aliases.setdefault(path[0], (path[0],))

    def resolve(self, func: ast.AST) -> tuple[str, ...]:
        if isinstance(func, ast.Name):
            return self.aliases.get(func.id, ())
        chain = attr_chain(func)
        if chain:
            prefix = self.aliases.get(chain[0], (chain[0],))
            return prefix + chain[1:]
        return ()


def _forbidden_call(node: ast.Call, imports: _ImportTable) -> str | None:
    """A human-readable reason when this call is a nondeterminism
    source, else None."""
    if isinstance(node.func, ast.Name) and node.func.id in ("id", "hash"):
        return (
            f"{node.func.id}() is process-local "
            f"(run-global identity / salted hash)"
        )
    chain = imports.resolve(node.func)
    if not chain:
        return None
    root, leaf = chain[0], chain[-1]
    if root == "time" and leaf in _TIME_FNS:
        return f"wall clock: {'.'.join(chain)}()"
    if root == "datetime" and leaf in _DATETIME_FNS:
        return f"wall clock: {'.'.join(chain)}()"
    if root == "os" and leaf == "urandom":
        return "os.urandom() is unseedable"
    if root == "uuid" and leaf.startswith("uuid"):
        return f"{'.'.join(chain)}() is a run-global id"
    if root == "random" and leaf in _RANDOM_FNS:
        return (
            f"module-level {'.'.join(chain)}() — use a seeded "
            f"random.Random(seed) instance"
        )
    if (
        root == "random"
        and leaf == "Random"
        and not node.args
        and not node.keywords
    ):
        return "unseeded random.Random() — pass an explicit seed"
    if len(chain) >= 3 and root in ("np", "numpy") and chain[1] == "random":
        if leaf == "default_rng" and (node.args or node.keywords):
            return None  # seeded generator (positional or seed=): sanctioned
        return (
            f"unseeded {'.'.join(chain)}() — use "
            f"np.random.default_rng(seed)"
        )
    return None


def _payload_sink(node: ast.Call) -> bool:
    """True for ``<anything>.flight.record(...)`` / ``flight.record(...)``:
    a flight-recorder payload construction site."""
    chain = attr_chain(node.func)
    return len(chain) >= 2 and chain[-2:] == ("flight", "record")


class DeterminismChecker:
    """Flags nondeterminism sources in seeded zones and in flight-
    recorder payloads anywhere."""

    rule = RULE

    def __init__(self, zones: tuple[Zone, ...] | None = None):
        if zones is None:
            from .zones import DETERMINISM_ZONES

            zones = DETERMINISM_ZONES
        self.zones = zones

    def check(
        self, rel_path: str, tree: ast.Module, source: str
    ) -> list[Finding]:
        findings: list[Finding] = []
        zone = zone_for(self.zones, rel_path)
        scopes = ScopeIndex(tree) if zone is not None else None
        imports = _ImportTable(tree)
        # Nodes already reported via a payload sink (sink findings carry
        # the better message; don't double-report inside det zones).
        sunk: set[ast.AST] = set()
        for unit in dataflow_units(tree):
            # Names bound (anywhere in this unit) from a forbidden call:
            # `now = time.time(); flight.record(..., at=now)` is the
            # same payload hazard as the inline spelling.
            tainted: dict[str, str] = {}
            for node in own_nodes(unit):
                if not isinstance(node, ast.Assign):
                    continue
                why = next(
                    (
                        w
                        for sub in ast.walk(node.value)
                        if isinstance(sub, ast.Call)
                        and (w := _forbidden_call(sub, imports)) is not None
                    ),
                    None,
                )
                if why is None:
                    continue
                # Direct name bindings only: `seq.stalled_since =
                # time.time()` stores into a field — it must not taint
                # the whole object `seq` (field-level taint is out of
                # scope; the inline spelling in a payload is caught).
                def name_targets(t: ast.AST):
                    if isinstance(t, ast.Name):
                        yield t.id
                    elif isinstance(t, ast.Tuple):
                        for e in t.elts:
                            yield from name_targets(e)

                for t in node.targets:
                    for name in name_targets(t):
                        tainted[name] = why
            for node in own_nodes(unit):
                if not isinstance(node, ast.Call) or not _payload_sink(node):
                    continue
                for sub in ast.walk(node):
                    if sub is node:
                        continue
                    why = None
                    if isinstance(sub, ast.Call):
                        why = _forbidden_call(sub, imports)
                    elif isinstance(sub, ast.Name) and sub.id in tainted:
                        why = f"{tainted[sub.id]} (via local {sub.id!r})"
                    if why is not None:
                        sunk.add(sub)
                        findings.append(
                            Finding(
                                rule=RULE,
                                file=rel_path,
                                line=sub.lineno,
                                col=sub.col_offset,
                                end_line=sub.end_lineno or sub.lineno,
                                message=(
                                    f"flight-recorder payloads must stay "
                                    f"bit-identical across same-seed runs; "
                                    f"{why}"
                                ),
                            )
                        )
        if zone is not None:
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or node in sunk:
                    continue
                if not scopes.in_scope(node, zone):
                    continue
                why = _forbidden_call(node, imports)
                if why is not None:
                    findings.append(
                        Finding(
                            rule=RULE,
                            file=rel_path,
                            line=node.lineno,
                            col=node.col_offset,
                            end_line=node.end_lineno or node.lineno,
                            message=f"seed-deterministic zone: {why}",
                        )
                    )
        return findings

    def check_source(self, rel_path: str, source: str) -> list[Finding]:
        return self.check(rel_path, ast.parse(source), source)
