"""PrefillTpuWorker: the prefill fleet of the disaggregated graphs.

Reference parity:
``/root/reference/examples/llm/components/prefill_worker.py`` (pull the
prefill queue, compute, write KV to the decode worker). TPU-native: the
queue rides the coordinator, KV pages travel over the TCP transfer
plane, and the worker registers a presence endpoint so the planner can
count the fleet.
"""

from __future__ import annotations

import asyncio
import logging

from dynamo_exp_tpu.sdk import async_on_start, dynamo_context, endpoint, service

logger = logging.getLogger(__name__)


@service(dynamo={"namespace": "dynamo"}, resources={"tpu": 1})
class PrefillTpuWorker:
    model_path: str = ""
    served_model_name: str = ""
    random_weights: bool = False
    page_size: int = 16
    num_pages: int = 0
    max_model_len: int = 2048
    kv_dtype: str = "bfloat16"

    def __init__(self):
        self.worker = None
        self._run_task = None

    @async_on_start
    async def start_engine(self) -> None:
        from dynamo_exp_tpu.disagg import PrefillWorker
        from dynamo_exp_tpu.models.hub import resolve_model_path
        from dynamo_exp_tpu.planner.planner import prefill_queue_name
        from dynamo_exp_tpu.run import build_tpu_engine
        from dynamo_exp_tpu.runtime.runtime import CancellationToken

        drt = dynamo_context["runtime"]

        class _Opts:
            model_path = resolve_model_path(self.model_path)
            model_name = self.served_model_name
            preset = ""
            random_weights = self.random_weights
            page_size = self.page_size
            num_pages = self.num_pages
            max_decode_slots = 2  # prefill-only: decode slots are parking
            max_model_len = self.max_model_len
            kv_dtype = self.kv_dtype
            host_cache_pages = 0
            max_tokens = 256
            tp = 1

        engine, _mdc = build_tpu_engine(_Opts)
        engine.start()
        queue = drt.work_queue(
            prefill_queue_name(self.served_model_name or "model")
        )
        # No component= here: the SDK already serves this service's
        # @endpoint("pull") for presence — a second registration would
        # double-count the fleet.
        self.worker = PrefillWorker(engine, queue, CancellationToken())
        self._run_task = asyncio.ensure_future(self.worker.run())

    # The planner counts the fleet through this presence endpoint; the
    # actual work arrives through the queue, never pushed requests.
    @endpoint("pull")
    async def pull(self, request: dict):
        yield {
            "served": self.worker.served if self.worker else 0,
            "failed": self.worker.failed if self.worker else 0,
        }
