"""Model-family coverage: qwen2 (QKV bias), mistral (sliding window),
mixtral (sparse MoE) — each checked against an independent oracle
(dense-dispatch MoE reference, masked dense attention, and HF
transformers forward for tiny random checkpoints)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.models import (
    TINY_MOE,
    TINY_QWEN2,
    ModelConfig,
    forward,
    init_kv_cache,
    init_params,
    param_shardings,
)

PS = 8


def _full_logits(params, cfg, token_list):
    T = len(token_list)
    pmax = (T + PS - 1) // PS
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
    tokens = jnp.array([token_list], dtype=jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    logits, _, _ = forward(params, cfg, tokens, positions, table, k, v)
    return np.asarray(logits[0])


def _f32_params(cfg, seed):
    return jax.tree.map(
        lambda x: x.astype(jnp.float32), init_params(jax.random.PRNGKey(seed), cfg)
    )


def test_moe_ffn_matches_dense_reference():
    from dynamo_exp_tpu.ops.moe import moe_ffn, moe_ffn_reference

    key = jax.random.PRNGKey(0)
    N, D, I, E, K = 17, 32, 48, 4, 2
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (N, D), jnp.float32)
    router = jax.random.normal(ks[1], (D, E), jnp.float32)
    wg = jax.random.normal(ks[2], (E, D, I), jnp.float32) * D**-0.5
    wu = jax.random.normal(ks[3], (E, D, I), jnp.float32) * D**-0.5
    wd = jax.random.normal(ks[4], (E, I, D), jnp.float32) * I**-0.5

    got = moe_ffn(x, router, wg, wu, wd, K)
    want = moe_ffn_reference(x, router, wg, wu, wd, K)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)

    # Unnormalised top-k weights (norm_topk_prob=False) must also agree.
    got = moe_ffn(x, router, wg, wu, wd, K, norm_topk_prob=False)
    want = moe_ffn_reference(x, router, wg, wu, wd, K, norm_topk_prob=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_sliding_window_matches_masked_dense():
    from dynamo_exp_tpu.ops import paged_attention, write_kv_pages

    key = jax.random.PRNGKey(1)
    B, T, H, Hkv, D, W = 2, 16, 4, 2, 8, 5
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)

    # Dense oracle with an explicit sliding-window mask.
    qg = q.reshape(B, T, Hkv, H // Hkv, D)
    scores = jnp.einsum("bthqd,bshd->bhqts", qg, k) * D**-0.5
    i = jnp.arange(T)[:, None]
    j = jnp.arange(T)[None, :]
    mask = (j <= i) & (j > i - W)
    probs = jax.nn.softmax(jnp.where(mask, scores, -1e30), axis=-1)
    want = jnp.einsum("bhqts,bshd->bthqd", probs, v).reshape(B, T, H, D)

    pmax = T // PS
    kc = jnp.zeros((B * pmax + 1, PS, Hkv * D))
    vc = jnp.zeros_like(kc)
    table = (jnp.arange(B * pmax, dtype=jnp.int32).reshape(B, pmax)) + 1
    pos = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
    flat = pos.reshape(-1)
    bidx = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)
    kc, vc = write_kv_pages(
        kc, vc, k.reshape(B * T, -1), v.reshape(B * T, -1),
        table[bidx, flat // PS], flat % PS, jnp.ones(B * T, bool),
    )
    got = paged_attention(q, kc, vc, table, pos, window=W)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_moe_incremental_decode_matches_full_prefill():
    cfg = TINY_MOE
    params = _f32_params(cfg, 7)
    toks = list(np.random.RandomState(2).randint(1, cfg.vocab_size, size=13))
    want = _full_logits(params, cfg, toks)

    pmax = 2
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    split = 9
    logits, k, v = forward(
        params, cfg,
        jnp.array([toks[:split]], jnp.int32),
        jnp.arange(split, dtype=jnp.int32)[None, :], table, k, v,
    )
    np.testing.assert_allclose(np.asarray(logits[0]), want[:split], rtol=1e-4, atol=1e-4)
    for i in range(split, len(toks)):
        logits, k, v = forward(
            params, cfg,
            jnp.array([[toks[i]]], jnp.int32),
            jnp.array([[i]], jnp.int32), table, k, v,
        )
        np.testing.assert_allclose(np.asarray(logits[0, 0]), want[i], rtol=1e-4, atol=1e-4)


def test_qwen2_bias_changes_logits_and_tp_matches():
    """Bias params must actually affect the forward (guard against the
    config knob parsing but the model ignoring it), and the tp-sharded
    qwen2 forward must agree with single-device."""
    from dynamo_exp_tpu.parallel import build_mesh, shard_pytree

    cfg = TINY_QWEN2
    params = _f32_params(cfg, 11)
    toks = list(np.random.RandomState(3).randint(1, cfg.vocab_size, size=9))
    want = _full_logits(params, cfg, toks)

    zeroed = jax.tree.map(lambda x: x, params)
    zeroed["layers"] = dict(zeroed["layers"])
    zeroed["layers"]["bq"] = jnp.zeros_like(params["layers"]["bq"])
    assert np.abs(_full_logits(zeroed, cfg, toks) - want).max() > 1e-6

    mesh = build_mesh(tp=2)
    sp, _ = shard_pytree(mesh, params, param_shardings(cfg))
    fwd = jax.jit(forward, static_argnums=(1,))
    T = len(toks)
    pmax = (T + PS - 1) // PS
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    logits, _, _ = fwd(
        sp, cfg,
        jnp.array([toks], jnp.int32),
        jnp.arange(T, dtype=jnp.int32)[None, :], table, k, v,
    )
    np.testing.assert_allclose(np.asarray(logits[0]), want, rtol=1e-3, atol=1e-3)


def test_moe_tp_sharded_matches_single_device():
    from dynamo_exp_tpu.parallel import build_mesh, shard_pytree

    cfg = TINY_MOE
    params = _f32_params(cfg, 13)
    toks = list(np.random.RandomState(5).randint(1, cfg.vocab_size, size=11))
    want = _full_logits(params, cfg, toks)

    mesh = build_mesh(tp=2)
    sp, _ = shard_pytree(mesh, params, param_shardings(cfg))
    fwd = jax.jit(forward, static_argnums=(1,))
    T = len(toks)
    pmax = (T + PS - 1) // PS
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    logits, _, _ = fwd(
        sp, cfg,
        jnp.array([toks], jnp.int32),
        jnp.arange(T, dtype=jnp.int32)[None, :], table, k, v,
    )
    np.testing.assert_allclose(np.asarray(logits[0]), want, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# HF transformers parity: tiny random checkpoints saved to disk, loaded by
# our loader, logits compared to the HF torch forward.
# ---------------------------------------------------------------------------

def _save_hf_model(tmp_path, hf_model, config):
    hf_model.save_pretrained(tmp_path, safe_serialization=True)
    with open(os.path.join(tmp_path, "config.json"), "w") as f:
        json.dump(config.to_dict(), f)


def _parity_check(tmp_path, hf_model, hf_config, n_tokens=12, atol=2e-3):
    import torch

    from dynamo_exp_tpu.models.loader import load_params

    hf_model = hf_model.eval()
    _save_hf_model(str(tmp_path), hf_model, hf_config)
    params, cfg = load_params(str(tmp_path))
    assert cfg.model_type == hf_config.model_type

    toks = list(np.random.RandomState(9).randint(1, cfg.vocab_size, size=n_tokens))
    with torch.no_grad():
        want = hf_model(torch.tensor([toks])).logits[0].float().numpy()
    got = _full_logits(params, cfg, toks)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=atol)


@pytest.fixture
def _hf_env(monkeypatch):
    """Requested only by the HF parity tests — NOT autouse, so the
    pure-JAX tests above keep running on hosts without torch."""
    monkeypatch.setenv("TRANSFORMERS_VERBOSITY", "error")
    monkeypatch.setenv("HF_HUB_OFFLINE", "1")
    torch = pytest.importorskip("torch")
    torch.manual_seed(0)  # deterministic random init → stable tolerances


@pytest.mark.slow  # full-logit torch parity: the longest single model
# proof; the per-family engine serve tests keep covering qwen2 in tier-1.
def test_hf_parity_qwen2(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        torch_dtype="float32",
    )
    _parity_check(tmp_path, transformers.Qwen2ForCausalLM(c), c)


def test_hf_parity_mistral_sliding_window(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.MistralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, sliding_window=6,
        torch_dtype="float32",
    )
    # attn_implementation="eager" honours sliding_window in small models.
    model = transformers.MistralForCausalLM._from_config(
        c, attn_implementation="eager"
    )
    _parity_check(tmp_path, model, c, n_tokens=16, atol=5e-3)


def test_hf_parity_mixtral(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, num_local_experts=4,
        num_experts_per_tok=2, sliding_window=None, torch_dtype="float32",
    )
    # Slightly looser: expert-sum accumulation order differs between
    # ragged_dot grouping and HF's per-expert index_add.
    _parity_check(tmp_path, transformers.MixtralForCausalLM(c), c, atol=5e-3)


def test_moe_expert_parallel_matches_single_device():
    """Experts sharded over the mesh's ep axis (moe_ffn_ep shard_map
    path) must match the single-device forward — alone and composed
    with tp (SURVEY §2.10 'mesh expert axis')."""
    from dynamo_exp_tpu.parallel import build_mesh, shard_pytree

    cfg = TINY_MOE
    params = _f32_params(cfg, 17)
    toks = list(np.random.RandomState(8).randint(1, cfg.vocab_size, size=10))
    want = _full_logits(params, cfg, toks)

    for ep, tp in ((2, 1), (2, 2)):
        mesh = build_mesh(tp=tp, ep=ep)
        sp, _ = shard_pytree(
            mesh, params, param_shardings(cfg, ep_axis="ep")
        )
        fwd = jax.jit(forward, static_argnums=(1,), static_argnames=("mesh",))
        T = len(toks)
        pmax = (T + PS - 1) // PS
        k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
        table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
        logits, _, _ = fwd(
            sp, cfg,
            jnp.array([toks], jnp.int32),
            jnp.arange(T, dtype=jnp.int32)[None, :], table, k, v,
            mesh=mesh,
        )
        np.testing.assert_allclose(
            np.asarray(logits[0]), want, rtol=1e-3, atol=1e-3,
            err_msg=f"ep={ep} tp={tp}",
        )


def test_hf_parity_qwen3(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.Qwen3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, tie_word_embeddings=False,
        torch_dtype="float32",
    )
    _parity_check(tmp_path, transformers.Qwen3ForCausalLM(c), c, atol=5e-3)


def test_hf_parity_qwen3_moe(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        max_position_embeddings=128, tie_word_embeddings=False,
        torch_dtype="float32",
    )
    _parity_check(
        tmp_path, transformers.Qwen3MoeForCausalLM(c), c, atol=5e-3
    )


def test_hf_parity_gemma(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.GemmaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, torch_dtype="float32",
    )
    # Gemma always ties embeddings; eager attention for exactness.
    model = transformers.GemmaForCausalLM._from_config(
        c, attn_implementation="eager"
    )
    _parity_check(tmp_path, model, c, atol=5e-3)


@pytest.mark.parametrize(
    "preset",
    ["tiny", "tiny-qwen2", "tiny-qwen3", "tiny-moe", "tiny-shared-moe",
     "tiny-gemma", "tiny-gemma2"]
)
async def test_engine_serves_every_family(preset):
    """Engine e2e per family: greedy decode through the full continuous-
    batching stack must equal the bare-forward oracle — catches family
    plumbing breaks (penalty counts, prefix cache, decode windows) that
    forward-level parity tests can't."""
    import dataclasses

    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models import PRESETS
    from dynamo_exp_tpu.parallel import single_device_mesh
    from dynamo_exp_tpu.protocols.common import BackendInput

    if preset == "tiny-gemma2":  # softcaps + alternating sliding window
        mcfg = dataclasses.replace(
            PRESETS["tiny"], hidden_act="gelu_tanh", rms_norm_offset=True,
            scale_embeddings=True, post_norms=True, attn_logit_softcap=50.0,
            final_logit_softcap=30.0, query_pre_attn_scalar=16.0,
            sliding_window=6, alt_sliding_window=True, model_type="gemma2",
        )
    elif preset == "tiny-shared-moe":  # qwen2_moe: shared expert + gate
        mcfg = dataclasses.replace(
            PRESETS["tiny-moe"], shared_expert_intermediate_size=80,
            norm_topk_prob=False, model_type="qwen2_moe",
        )
    elif preset == "tiny-gemma":
        mcfg = dataclasses.replace(
            PRESETS["tiny"], hidden_act="gelu_tanh", rms_norm_offset=True,
            scale_embeddings=True, model_type="gemma",
        )
    else:
        mcfg = PRESETS[preset]
    cfg = EngineConfig(
        model=mcfg, max_decode_slots=2, page_size=PS, num_pages=32,
        max_model_len=128, eos_token_ids=[],
    )
    engine = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    engine.start()
    try:
        prompt = [5, 9, 17, 3, 11, 2]
        params = engine.params
        pmax = 8
        k, v = init_kv_cache(mcfg, num_pages=pmax + 1, page_size=PS)
        table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
        logits, k, v = forward(
            params, mcfg,
            jnp.array([prompt], jnp.int32),
            jnp.arange(len(prompt), dtype=jnp.int32)[None, :], table, k, v,
        )
        want = [int(np.asarray(logits)[0, -1].argmax())]
        for _ in range(4):
            pos = len(prompt) + len(want) - 1
            logits, k, v = forward(
                params, mcfg,
                jnp.array([[want[-1]]], jnp.int32),
                jnp.array([[pos]], jnp.int32), table, k, v,
            )
            want.append(int(np.asarray(logits)[0, 0].argmax()))

        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 5
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        got = []
        async for item in stream:
            got.extend(item.get("token_ids", []))
        assert got == want, f"family {preset} engine/oracle mismatch"
    finally:
        engine.stop()


def test_hf_parity_qwen2_moe(tmp_path, _hf_env):
    transformers = pytest.importorskip("transformers")
    c = transformers.Qwen2MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, shared_expert_intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        max_position_embeddings=128, tie_word_embeddings=False,
        torch_dtype="float32",
    )
    _parity_check(
        tmp_path, transformers.Qwen2MoeForCausalLM(c), c, atol=5e-3
    )


def test_hf_parity_gemma2(tmp_path, _hf_env):
    """gemma2: 4 norms/layer, attn+final softcaps, query scale, and
    sliding window on alternating layers (T > window exercises the
    alternation)."""
    transformers = pytest.importorskip("transformers")
    c = transformers.Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, sliding_window=6,
        query_pre_attn_scalar=8, attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0, torch_dtype="float32",
    )
    model = transformers.Gemma2ForCausalLM._from_config(
        c, attn_implementation="eager"
    )
    _parity_check(tmp_path, model, c, n_tokens=16, atol=5e-3)


def test_hf_parity_phi3(tmp_path, _hf_env):
    """phi3: llama-shaped with packed qkv_proj / gate_up_proj tensors
    (the loader splits them)."""
    transformers = pytest.importorskip("transformers")
    c = transformers.Phi3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, pad_token_id=0, torch_dtype="float32",
    )
    model = transformers.Phi3ForCausalLM._from_config(
        c, attn_implementation="eager"
    )
    _parity_check(tmp_path, model, c, atol=5e-3)


def test_hf_parity_gemma3(tmp_path, _hf_env):
    """gemma3 text: explicit layer_types (sliding/full), dual rope base
    (local 10k on sliding layers, rope_theta on full), q/k norm, 4-norm
    layers, no softcaps."""
    transformers = pytest.importorskip("transformers")
    c = transformers.Gemma3TextConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, sliding_window=6,
        sliding_window_pattern=2, rope_theta=1000000.0,
        rope_local_base_freq=10000.0, query_pre_attn_scalar=8,
        torch_dtype="float32",
    )
    model = transformers.Gemma3ForCausalLM._from_config(
        c, attn_implementation="eager"
    )
    # Slightly looser: four offset-norms per layer in float32 accumulate
    # more ordering noise than the other families.
    _parity_check(tmp_path, model, c, n_tokens=16, atol=8e-3)


def test_gemma3_layer_types_from_pattern():
    """Older gemma3 configs with only sliding_window_pattern derive the
    explicit layer kinds (every Nth layer full attention)."""
    cfg = ModelConfig.from_hf_config({
        "model_type": "gemma3_text", "num_hidden_layers": 6,
        "sliding_window": 512, "sliding_window_pattern": 3,
        "rope_local_base_freq": 10000.0,
    })
    assert cfg.layer_types == (
        "sliding_attention", "sliding_attention", "full_attention",
        "sliding_attention", "sliding_attention", "full_attention",
    )
