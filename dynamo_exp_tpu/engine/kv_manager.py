"""Host-side KV page pool: allocation, content-addressed prefix reuse,
LRU eviction, and KV event emission.

Capability parity with the reference's KV block manager
(``/root/reference/lib/llm/src/kv/reuse.rs:50-760`` — the
``AvailableBlocks`` match/take/update actor — and ``kv/manager.rs:22-168``
G1/G2 tiers), redesigned for the TPU engine:

- Device pages live in the paged pools allocated by ``models/llama.py``;
  this manager only tracks *ids* — all data movement happens inside the
  jitted forward (writes) or via host offload (``offload.py``).
- Reuse is content-addressed by the chained sequence hash of each full
  page (``tokens.py``), so a new request's prompt prefix maps onto pages
  already resident in HBM; matched pages are ref-counted, and pages whose
  refs drop to zero park in an LRU from which they can be revived (hit)
  or evicted (miss → reallocated).
- Every registered/evicted full page emits a KV event (stored/removed)
  through a callback — the feed for the KV-aware router's radix index
  (reference: ``lib/llm/src/kv_router/publisher.rs:34-139``).
"""

from __future__ import annotations

import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Sequence

from ..tokens import compute_block_hashes_for_seq

if TYPE_CHECKING:
    import numpy as np

    from .offload import HostKvPool


@dataclass
class PageRecord:
    page_id: int
    seq_hash: int | None = None  # None until the page is full + registered
    ref_count: int = 0


@dataclass
class Allocation:
    """Result of ``allocate_sequence``.

    ``page_ids`` covers ceil(len(tokens)/page_size) pages; ``cached_len``
    (a multiple of page_size) counts G1-matched plus G2-uploaded pages;
    ``uploads`` lists (page_id, seq_hash, k_page, v_page) host pages the
    engine must inject before prefill; ``hashes`` are the chained
    sequence hashes of every full prompt page (computed once here so the
    scheduler never rehashes the prompt)."""

    page_ids: list[int]
    cached_len: int
    uploads: list
    hashes: list[int]


@dataclass
class KvLease:
    """A pin on extracted pages during a disaggregation KV handoff.

    The prefill worker extracts a sequence's pages for the wire while
    the owning sequence finishes — without a lease the pages would park
    in the reclaimable LRU and could be evicted (or, under the handoff
    contract, be considered delivered) before the decode worker confirms
    receipt. The lease takes one extra reference per page; delivery
    confirmation (``confirm_lease``) releases it, and the reaper
    (``reap_expired``) reclaims orphans when the decode instance dies
    between extract and inject — so failover never strands HBM.

    State machine (docs/fault_tolerance.md "Resumable streams"):
    GRANTED → CONFIRMED (transfer acked end-to-end) | EXPIRED (reaped).
    """

    lease_id: str
    page_ids: list[int]
    expires_at: float  # manager-clock seconds


@dataclass
class KvEvent:
    """Stored/removed notification for the router's radix index."""

    kind: str  # "stored" | "removed"
    seq_hashes: list[int]
    parent_hash: int | None = None
    token_blocks: list[list[int]] | None = None  # only on stored
    ts: float = field(default_factory=time.time)


class KvPageManager:
    """Tracks ownership and reuse of the device page pool by id.

    Not thread-safe by design: owned by the engine loop thread, the same
    single-writer discipline the reference uses for its block pool actor.
    """

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        event_cb: Callable[[KvEvent], None] | None = None,
        host_pool: "HostKvPool | None" = None,
        on_evict: Callable[[int, int], None] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.num_pages = num_pages
        self.page_size = page_size
        self.event_cb = event_cb
        self.clock = clock
        # G2 tier: evicted device pages are offloaded (via ``on_evict``,
        # which the engine wires to a device gather + CopyStream) and
        # matched back in from ``host_pool`` on later prompts.
        self.host_pool = host_pool
        self.on_evict = on_evict
        self._records: dict[int, PageRecord] = {
            i: PageRecord(i) for i in range(num_pages)
        }
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        # seq_hash -> page_id for every registered full page still resident.
        self._by_hash: dict[int, int] = {}
        # Zero-ref registered pages, LRU order (oldest first).
        self._reclaimable: OrderedDict[int, None] = OrderedDict()
        # Disaggregation handoff leases, by lease id (single-writer like
        # everything else here: only the engine loop thread touches them).
        self._leases: dict[str, KvLease] = {}
        self.lease_reclaimed_pages = 0  # pages freed by the reaper
        # Metrics counters.
        self.hits = 0
        self.misses = 0
        # G2 (host offload tier) hit/miss: of the pages a prompt needed
        # beyond its G1 device match, how many the host tier supplied.
        self.offload_hits = 0
        self.offload_misses = 0

    # ---------------------------------------------------------------- stats
    @property
    def free_pages(self) -> int:
        return len(self._free) + len(self._reclaimable)

    @property
    def active_pages(self) -> int:
        return self.num_pages - self.free_pages

    @property
    def usage(self) -> float:
        return self.active_pages / max(self.num_pages, 1)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def offload_hit_rate(self) -> float:
        total = self.offload_hits + self.offload_misses
        return self.offload_hits / total if total else 0.0

    def gauges(self) -> dict:
        """Engine-level KV gauges for the telemetry registry."""
        return {
            "hbm_page_occupancy": self.usage,
            "offload_hit_rate": self.offload_hit_rate(),
        }

    # ------------------------------------------------------------ allocation
    def match_prefix(self, tokens: Sequence[int]) -> tuple[list[int], list[int]]:
        """Longest resident prefix of ``tokens`` in full pages.

        Returns (page_ids, seq_hashes) of the matched prefix — does NOT
        take references; call ``allocate_sequence`` to commit.
        """
        return self._match_hashes(
            compute_block_hashes_for_seq(tokens, self.page_size)
        )

    def _match_hashes(self, hashes: list[int]) -> tuple[list[int], list[int]]:
        pages: list[int] = []
        matched: list[int] = []
        for h in hashes:
            pid = self._by_hash.get(h)
            if pid is None:
                break
            pages.append(pid)
            matched.append(h)
        return pages, matched

    def allocate_sequence(
        self, tokens: Sequence[int], max_pages: int
    ) -> Allocation | None:
        """Pages for a new sequence: reuse the longest device-resident
        (G1) prefix, extend it from the host tier (G2), then fresh pages
        for the rest of the prompt.

        Returns an ``Allocation`` or None if the pool can't satisfy the
        request right now (caller re-queues).
        """
        ps = self.page_size
        need_total = (len(tokens) + ps - 1) // ps
        if need_total > max_pages:
            return None  # exceeds per-sequence capacity; caller must reject
        hashes = compute_block_hashes_for_seq(tokens, ps)
        matched_pages, matched_hashes = self._match_hashes(hashes)
        # Extend the match into the host tier — match first (no copies);
        # pages are fetched only once the allocation is known to succeed,
        # so a pool-exhausted retry loop never repeats the memcpys.
        g2_hashes: list[int] = []
        if self.host_pool is not None:
            g2_hashes = self.host_pool.match_chain(hashes[len(matched_pages) :])
        # Never reuse the *entire* prompt: the last token's KV must be
        # recomputed into a page this sequence owns so decode can append.
        while (
            matched_pages or g2_hashes
        ) and (len(matched_pages) + len(g2_hashes)) * ps >= len(tokens):
            if g2_hashes:
                g2_hashes.pop()
            else:
                matched_pages.pop()
                matched_hashes.pop()
        need_fresh = need_total - len(matched_pages)
        # Matched parked pages are about to leave the reclaimable LRU
        # (_ref_page below); counting them as takeable here would let
        # _take_free pop an empty LRU and crash the engine loop.
        parked_matches = sum(
            1 for pid in matched_pages if self._records[pid].ref_count == 0
        )
        if need_fresh > self._available_for_take() - parked_matches:
            return None
        # fetch() copies each page out under the pool lock, so a
        # concurrent LRU eviction can't corrupt it before injection; a
        # miss (evicted since match) just shortens the restored prefix.
        host_pages: list[tuple[int, "np.ndarray", "np.ndarray"]] = []
        for h in g2_hashes:
            data = self.host_pool.fetch(h)
            if data is None:
                break
            host_pages.append((h, data[0], data[1]))
        for pid in matched_pages:  # commit the reuse
            self._ref_page(pid)
        fresh = [self._take_free() for _ in range(need_fresh)]
        uploads = [
            (fresh[j], h, k, v) for j, (h, k, v) in enumerate(host_pages)
        ]
        self.hits += len(matched_pages) + len(host_pages)
        self.misses += need_fresh - len(host_pages)
        if self.host_pool is not None:
            self.offload_hits += len(host_pages)
            self.offload_misses += need_fresh - len(host_pages)
        cached = (len(matched_pages) + len(host_pages)) * ps
        return Allocation(matched_pages + fresh, cached, uploads, hashes)

    def allocate_page(self) -> int | None:
        """One fresh page (decode crossing a page boundary)."""
        if self._available_for_take() < 1:
            return None
        return self._take_free()

    # ------------------------------------------------------------- lifecycle
    def register_full_page(
        self,
        page_id: int,
        seq_hash: int,
        parent_hash: int | None = None,
        tokens: list[int] | None = None,
    ) -> None:
        """A page just got its page_size-th token: make it reusable and
        announce it to the router index."""
        rec = self._records[page_id]
        if rec.seq_hash == seq_hash:
            return
        # A different page may already hold this content (two requests with
        # the same prompt racing); keep the first registration authoritative.
        if seq_hash not in self._by_hash:
            rec.seq_hash = seq_hash
            self._by_hash[seq_hash] = page_id
            if self.event_cb:
                self.event_cb(
                    KvEvent(
                        "stored",
                        [seq_hash],
                        parent_hash=parent_hash,
                        token_blocks=[tokens] if tokens else None,
                    )
                )

    def release_sequence(self, page_ids: Sequence[int]) -> None:
        """Sequence finished: drop refs. Registered pages park in the LRU
        (still matchable); unregistered pages return to the free list."""
        for pid in page_ids:
            rec = self._records[pid]
            if rec.ref_count > 0:
                rec.ref_count -= 1
            if rec.ref_count == 0:
                if rec.seq_hash is not None:
                    self._reclaimable[pid] = None
                    self._reclaimable.move_to_end(pid)
                else:
                    self._free.append(pid)

    # ---------------------------------------------------------------- leases
    @property
    def active_leases(self) -> int:
        return len(self._leases)

    def grant_lease(self, page_ids: Sequence[int], ttl_s: float) -> str:
        """Pin ``page_ids`` (one extra ref each) for a KV handoff in
        flight; returns the lease id the wire protocol carries. Must be
        called while the pages are still referenced (before the owning
        sequence is released), i.e. on the engine loop thread."""
        for pid in page_ids:
            self._ref_page(pid)
        lease = KvLease(
            lease_id=uuid.uuid4().hex,
            page_ids=list(page_ids),
            expires_at=self.clock() + ttl_s,
        )
        self._leases[lease.lease_id] = lease
        return lease.lease_id

    def confirm_lease(self, lease_id: str) -> bool:
        """Delivery confirmed: drop the lease's pins. Registered pages
        park in the reclaimable LRU exactly as a finished sequence's
        would. Unknown/already-reaped ids are a no-op (the confirm raced
        the reaper)."""
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return False
        self.release_sequence(lease.page_ids)
        return True

    def reap_expired(self, now: float | None = None) -> int:
        """Reclaim every expired lease's pages; returns pages freed.
        Engine-loop-thread only (mutates the free lists)."""
        now = self.clock() if now is None else now
        reclaimed = 0
        for lid in [
            lid for lid, l in self._leases.items() if now >= l.expires_at
        ]:
            lease = self._leases.pop(lid)
            self.release_sequence(lease.page_ids)
            reclaimed += len(lease.page_ids)
        self.lease_reclaimed_pages += reclaimed
        return reclaimed

    # -------------------------------------------------------------- internal
    def _available_for_take(self) -> int:
        return len(self._free) + len(self._reclaimable)

    def _ref_page(self, pid: int) -> None:
        rec = self._records[pid]
        if rec.ref_count == 0:
            self._reclaimable.pop(pid, None)
        rec.ref_count += 1

    def _take_free(self) -> int:
        if self._free:
            pid = self._free.pop()
        else:
            # Evict the least-recently-used parked page.
            pid, _ = self._reclaimable.popitem(last=False)
            self._evict(pid)
        rec = self._records[pid]
        rec.ref_count = 1
        rec.seq_hash = None
        return pid

    def _evict(self, pid: int) -> None:
        rec = self._records[pid]
        if rec.seq_hash is not None:
            if self.on_evict is not None:
                # Offload to G2 before the page can be overwritten: the
                # engine dispatches the on-device gather synchronously
                # here (stream order protects it from the next forward).
                self.on_evict(pid, rec.seq_hash)
            self._by_hash.pop(rec.seq_hash, None)
            if self.event_cb:
                self.event_cb(KvEvent("removed", [rec.seq_hash]))
            rec.seq_hash = None
