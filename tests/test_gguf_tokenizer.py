"""GGUF-embedded tokenizer reconstruction + SentencePiece backend.

Reference capability anchors: ``lib/llm/src/gguf/gguf_tokenizer.rs``
(rebuild a working tokenizer from tokenizer.ggml.* so a bare .gguf
serves without side files) and ``lib/llm/src/tokenizers/sp.rs``
(tokenizer.model loading). Here both backends converge on the same HF
``tokenizers`` Unigram/BPE construction, checked against oracles built
directly with that library and against the repo's BPE test fixture.
"""

import json
import os
import struct

import pytest

from dynamo_exp_tpu.gguf_tokenizer import (
    TOKEN_CONTROL,
    TOKEN_NORMAL,
    TOKEN_UNKNOWN,
    tokenizer_backend_from_gguf,
    tokenizer_from_gguf,
)
from dynamo_exp_tpu.model_card import ModelDeploymentCard
from dynamo_exp_tpu.models.gguf import GGUFFile, write_gguf
from dynamo_exp_tpu.sp_model import (
    parse_sentencepiece_model,
    tokenizer_backend_from_sp,
)
from dynamo_exp_tpu.tokenizer import Tokenizer

from .fixtures import build_tiny_model_dir

SAMPLES = [
    "hello world, this is a test.",
    "The quick brown fox jumps over the lazy dog",
    "numbers 123 and symbols !?",
]


# ------------------------------------------------------------------ BPE
def test_bpe_gguf_matches_source_tokenizer(tmp_path):
    """Write the fixture BPE tokenizer's vocab+merges into a GGUF and
    reconstruct: encodes must match the original tokenizer.json."""
    import tokenizers as hf_tok

    model_dir = build_tiny_model_dir(str(tmp_path / "m"))
    src = hf_tok.Tokenizer.from_file(os.path.join(model_dir, "tokenizer.json"))
    tj = json.load(open(os.path.join(model_dir, "tokenizer.json")))
    vocab = tj["model"]["vocab"]
    merges = tj["model"]["merges"]
    merges = [m if isinstance(m, str) else " ".join(m) for m in merges]
    tokens = [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]

    gpath = str(tmp_path / "t.gguf")
    write_gguf(
        gpath,
        {
            "general.architecture": "llama",
            "tokenizer.ggml.model": "gpt2",
            "tokenizer.ggml.tokens": tokens,
            "tokenizer.ggml.merges": merges,
            "tokenizer.ggml.eos_token_id": 0,
        },
        {},
    )
    rebuilt = tokenizer_backend_from_gguf(GGUFFile.parse(gpath))
    for text in SAMPLES:
        assert rebuilt.encode(text).ids == src.encode(text).ids
        assert rebuilt.decode(src.encode(text).ids) == src.decode(
            src.encode(text).ids
        )


# -------------------------------------------------------------- Unigram
def _unigram_fixture():
    """A tiny SP-style unigram vocab: specials + words + ascii bytes."""
    pieces = [("<unk>", 0.0), ("<s>", 0.0), ("</s>", 0.0)]
    words = ["▁hello", "▁world", "▁test", "▁the", "lo", "wor", "ld", "he"]
    pieces += [(w, -float(i + 1)) for i, w in enumerate(words)]
    pieces += [(chr(c), -20.0) for c in range(ord(" "), ord("~") + 1)]
    return pieces


def test_unigram_gguf_matches_direct_construction(tmp_path):
    from tokenizers import Tokenizer as HFTokenizer

    pieces = _unigram_fixture()
    from dynamo_exp_tpu.gguf_tokenizer import _build_unigram

    oracle = _build_unigram(
        [p for p, _ in pieces], [s for _, s in pieces], unk_id=0
    )

    gpath = str(tmp_path / "u.gguf")
    token_type = [TOKEN_UNKNOWN, TOKEN_CONTROL, TOKEN_CONTROL] + [
        TOKEN_NORMAL
    ] * (len(pieces) - 3)
    write_gguf(
        gpath,
        {
            "general.architecture": "llama",
            "tokenizer.ggml.model": "llama",
            "tokenizer.ggml.tokens": [p for p, _ in pieces],
            "tokenizer.ggml.scores": [float(s) for _, s in pieces],
            "tokenizer.ggml.token_type": token_type,
            "tokenizer.ggml.bos_token_id": 1,
            "tokenizer.ggml.eos_token_id": 2,
            # Pinned off: this test compares raw unigram segmentation
            # against an oracle with no BOS post-processor (the absent-key
            # default for SPM vocabs is True, matching llama.cpp).
            "tokenizer.ggml.add_bos_token": False,
        },
        {},
    )
    rebuilt = tokenizer_backend_from_gguf(GGUFFile.parse(gpath))
    assert isinstance(rebuilt, HFTokenizer)
    for text in ("hello world", "the test", "hello the world test"):
        assert rebuilt.encode(text).ids == oracle.encode(text).ids
        assert rebuilt.decode(rebuilt.encode(text).ids) == text

    # Facade: eos wired from metadata; decode skips specials.
    tok = tokenizer_from_gguf(gpath)
    assert tok.eos_token_ids == [2]


def test_unigram_gguf_add_bos_prepends(tmp_path):
    pieces = _unigram_fixture()
    gpath = str(tmp_path / "b.gguf")
    write_gguf(
        gpath,
        {
            "tokenizer.ggml.model": "llama",
            "tokenizer.ggml.tokens": [p for p, _ in pieces],
            "tokenizer.ggml.scores": [float(s) for _, s in pieces],
            "tokenizer.ggml.bos_token_id": 1,
            "tokenizer.ggml.eos_token_id": 2,
            "tokenizer.ggml.add_bos_token": True,
        },
        {},
    )
    rebuilt = tokenizer_backend_from_gguf(GGUFFile.parse(gpath))
    ids = rebuilt.encode("hello world").ids
    assert ids[0] == 1  # BOS prepended


# ------------------------------------------------------- SentencePiece
def _encode_sp_model(pieces, unk=0, bos=1, eos=2) -> bytes:
    """Hand-encode a minimal sentencepiece ModelProto."""

    def varint(n: int) -> bytes:
        out = b""
        while True:
            b = n & 0x7F
            n >>= 7
            out += bytes([b | (0x80 if n else 0)])
            if not n:
                return out

    def ld(field: int, payload: bytes) -> bytes:
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    def f32(field: int, v: float) -> bytes:
        return varint((field << 3) | 5) + struct.pack("<f", v)

    def vi(field: int, v: int) -> bytes:
        return varint((field << 3) | 0) + varint(v)

    out = b""
    for piece, score, ptype in pieces:
        body = ld(1, piece.encode()) + f32(2, score) + vi(3, ptype)
        out += ld(1, body)
    trainer = vi(40, unk) + vi(41, bos) + vi(42, eos)
    out += ld(2, trainer)
    return out


def test_sp_model_parse_and_tokenize(tmp_path):
    from dynamo_exp_tpu.sp_model import SP_CONTROL, SP_NORMAL, SP_UNKNOWN

    pieces = [
        ("<unk>", 0.0, SP_UNKNOWN),
        ("<s>", 0.0, SP_CONTROL),
        ("</s>", 0.0, SP_CONTROL),
    ] + [
        (p, s, SP_NORMAL)
        for p, s in _unigram_fixture()[3:]
    ]
    sp_path = str(tmp_path / "tokenizer.model")
    with open(sp_path, "wb") as f:
        f.write(_encode_sp_model(pieces))

    parsed, special_ids = parse_sentencepiece_model(sp_path)
    assert [p for p, _, _ in parsed] == [p for p, _, _ in pieces]
    assert special_ids == {"unk": 0, "bos": 1, "eos": 2}

    backend = tokenizer_backend_from_sp(sp_path)
    ids = backend.encode("hello world").ids
    assert ids[0] == 1  # bos prepended by default (HF llama behavior)
    assert backend.decode(ids, skip_special_tokens=True) == "hello world"


def test_from_pretrained_resolves_sp_dir(tmp_path):
    """A model dir with only tokenizer.model (no tokenizer.json) loads
    through the SentencePiece backend."""
    from dynamo_exp_tpu.sp_model import SP_CONTROL, SP_NORMAL, SP_UNKNOWN

    d = tmp_path / "spdir"
    d.mkdir()
    pieces = [
        ("<unk>", 0.0, SP_UNKNOWN),
        ("<s>", 0.0, SP_CONTROL),
        ("</s>", 0.0, SP_CONTROL),
    ] + [(p, s, SP_NORMAL) for p, s in _unigram_fixture()[3:]]
    with open(d / "tokenizer.model", "wb") as f:
        f.write(_encode_sp_model(pieces))
    tok = Tokenizer.from_pretrained(str(d))
    enc = tok.encode("hello test", add_special_tokens=True)
    assert tok.decode(enc.ids) == "hello test"


# ----------------------------------------------------- self-contained GGUF
def test_bare_gguf_serves_chat_via_mdc(tmp_path):
    """The headline property: a single .gguf file yields card + tokenizer
    + chat template — enough to build the OpenAI preprocessor chain."""
    pieces = _unigram_fixture()
    gpath = str(tmp_path / "model.gguf")
    tpl = (
        "{% for message in messages %}<|{{ message.role }}|>"
        "{{ message.content }}{% endfor %}<|assistant|>"
    )
    write_gguf(
        gpath,
        {
            "general.architecture": "llama",
            "general.name": "tiny-gguf-chat",
            "llama.context_length": 512,
            "tokenizer.ggml.model": "llama",
            "tokenizer.ggml.tokens": [p for p, _ in pieces],
            "tokenizer.ggml.scores": [float(s) for _, s in pieces],
            "tokenizer.ggml.bos_token_id": 1,
            "tokenizer.ggml.eos_token_id": 2,
            "tokenizer.chat_template": tpl,
        },
        {},
    )
    mdc = ModelDeploymentCard.from_gguf(gpath)
    assert mdc.display_name == "tiny-gguf-chat"
    assert mdc.context_length == 512
    assert mdc.eos_token_ids == [2]
    assert mdc.chat_template == tpl

    from dynamo_exp_tpu.preprocessor.preprocessor import OpenAIPreprocessor
    from dynamo_exp_tpu.protocols.openai import ChatCompletionRequest

    pp = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest(
        model="tiny-gguf-chat",
        messages=[{"role": "user", "content": "hello world"}],
    )
    b = pp.preprocess_chat(req)
    text = pp.tokenizer.decode(b.token_ids)
    assert "hello world" in text and "<|assistant|>" in text
