"""KV-page transfer plane: direct TCP between prefill and decode workers.

The reference moves KV blocks GPU→GPU with *incremental* NIXL/UCX RDMA
writes plus a completion notification (``/root/reference/container/
deps/vllm/…patch:1040-1862`` issues per-block writes as blocks finish).
On TPU there is no peer-to-peer RDMA library; the equivalent is
host-bounce: the prefill engine gathers pages to host numpy (XLA
dynamic-slice + device→host DMA), this plane ships the bytes, and the
decode engine injects them (host→device DMA + scatter).

Framing mirrors the reference's incremental writes: a BEGIN frame, then
``chunk_pages``-page DATA frames under a bounded in-flight ack window
(sender never buffers more than ``window`` unacked frames on the wire),
then END. An 8B model at 3k ISL is hundreds of MB of KV — one giant
frame would hold that entire payload in RAM at both ends and deliver
nothing until the last byte; chunking caps per-frame memory and lets
the receiver consume (and ultimately inject) pages while later pages
are still in flight (``KvPageReceiver.expect(on_chunk=...)``).

Dtype note: pages travel as raw bytes tagged with the dtype name;
bfloat16 numpy arrays (via ml_dtypes) round-trip through
``tobytes``/``frombuffer`` losslessly.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time

import jax.numpy as jnp
import numpy as np

from ..runtime.transports.codec import (
    MsgType,
    TwoPartMessage,
    read_message,
    write_message,
)
from ..telemetry import TraceContext, current_trace, get_telemetry, wire_headers
from ..telemetry.fleet import get_transfer_ledger

logger = logging.getLogger(__name__)


def _dtype_from_name(name: str) -> np.dtype:
    if name == "bfloat16":
        return np.dtype(jnp.bfloat16)
    return np.dtype(name)


# Inbound KV pages that failed their per-page wire checksum — every
# restore path that decodes pages off the wire (disagg inject AND the
# reclaim migration sink, both through KvPageReceiver._handle) verifies
# before the bytes can become matchable KV; a mismatch fails the
# transfer and the request degrades to local/journal prefill
# (token-identical). Mirrored as engine.metrics()
# ``kv_wire_checksum_failures`` and dynamo_kv_checksum_failures_total
# {path="wire"}.
_WIRE_CHECKSUM_FAILURES = 0


def wire_checksum_failures() -> int:
    return _WIRE_CHECKSUM_FAILURES


def encode_pages(pages: list[tuple[np.ndarray, np.ndarray]]) -> tuple[dict, bytes]:
    """Pack [(k_page, v_page), ...] into (header, payload). The header
    carries a per-page CRC32 over each page's K+V bytes (``sums``) so
    the receive side verifies content end-to-end — the framing codec's
    transport is reliable, but the page bytes traverse two host copies
    and (in chaos runs) seeded corruption on either side."""
    import zlib

    if not pages:
        return {"n_pages": 0, "shape": [], "dtype": "float32", "sums": []}, b""
    shape = list(pages[0][0].shape)
    dtype = pages[0][0].dtype
    buf = bytearray()
    sums: list[int] = []
    for k, v in pages:
        kb = np.ascontiguousarray(k).tobytes()
        vb = np.ascontiguousarray(v).tobytes()
        sums.append(zlib.crc32(vb, zlib.crc32(kb)))
        buf += kb
        buf += vb
    return {
        "n_pages": len(pages), "shape": shape, "dtype": str(dtype),
        "sums": sums,
    }, bytes(buf)


def decode_pages(header: dict, payload: bytes) -> list[tuple[np.ndarray, np.ndarray]]:
    """Unpack pages, verifying each against the header's per-page CRC
    when present (older senders omit ``sums``; their frames decode
    unverified for compatibility). A mismatch raises ``ValueError`` —
    the receiver fails the transfer future and the restore path falls
    back to re-prefill rather than ever serving the corrupt page."""
    import zlib

    global _WIRE_CHECKSUM_FAILURES
    n = header["n_pages"]
    if n == 0:
        return []
    shape = tuple(header["shape"])
    dtype = _dtype_from_name(header["dtype"])
    per = int(np.prod(shape)) * dtype.itemsize
    sums = header.get("sums")
    pages = []
    for i in range(n):
        off = i * 2 * per
        if sums is not None:
            crc = zlib.crc32(payload[off : off + 2 * per])
            if crc != sums[i]:
                _WIRE_CHECKSUM_FAILURES += 1
                get_telemetry().kv_checksum_failures.labels("wire").inc()
                raise ValueError(
                    f"KV wire checksum mismatch on page {i}/{n}"
                )
        k = np.frombuffer(payload, dtype, count=int(np.prod(shape)), offset=off)
        v = np.frombuffer(payload, dtype, count=int(np.prod(shape)), offset=off + per)
        pages.append((k.reshape(shape), v.reshape(shape)))
    return pages


# Defaults for the chunked transfer: pages per DATA frame and the
# bounded number of unacked frames in flight.
DEFAULT_CHUNK_PAGES = 4
DEFAULT_WINDOW = 4


async def send_kv_pages(
    return_addr: str,
    request_id: str,
    first_token: int,
    pages: list[tuple[np.ndarray, np.ndarray]],
    error: str | None = None,
    chunk_pages: int = DEFAULT_CHUNK_PAGES,
    window: int = DEFAULT_WINDOW,
    lease: "object | None" = None,  # disagg.protocol.LeaseGrant
    dst_instance: str = "",
    extra_header: dict | None = None,
) -> None:
    """Deliver one prefill result (or failure notice) to a decode worker.

    Pages go out as ``chunk_pages``-page DATA frames with at most
    ``window`` frames unacknowledged — per-frame memory at both ends is
    capped at ``chunk_pages * page_bytes`` regardless of prompt length,
    and arrival overlaps transmission. ``lease`` (if the sender pinned
    the source pages under a handoff lease) rides the BEGIN frame so the
    receive side can trace which lease covered the transfer; a clean
    final ack is the sender's cue to confirm the lease.

    ``dst_instance`` names the receiving decode worker for the per-link
    :class:`~dynamo_exp_tpu.telemetry.fleet.TransferLedger` (falls back
    to the return address); the sender's own instance identity rides
    the BEGIN frame so the receive side ledgers the same link by name.
    """
    host, _, port = return_addr.rpartition(":")
    t0 = time.time()
    total_bytes = 0
    tel = get_telemetry()
    try:
        reader, writer = await asyncio.open_connection(
            host or "127.0.0.1", int(port)
        )
    except BaseException:
        tel.kv_transfer_total.labels("send", "error").inc()
        raise

    async def _read_ack() -> None:
        """An ack that is an ERROR frame (or ok=False) means the receiver
        rejected the transfer — the sender must NOT treat it as delivery
        and release its device pages."""
        ack = await read_message(reader)
        if ack.msg_type == MsgType.ERROR or ack.header.get("ok") is False:
            raise RuntimeError(
                f"KV transfer rejected by receiver: "
                f"{ack.header.get('error', 'unknown error')}"
            )

    try:
        if error is not None:
            await write_message(
                writer,
                TwoPartMessage(
                    MsgType.ERROR, {"request_id": request_id, "error": error}
                ),
            )
            await read_message(reader)
            return
        chunks = [
            pages[i : i + chunk_pages]
            for i in range(0, len(pages), chunk_pages)
        ]
        begin = {
            "request_id": request_id,
            "first_token": first_token,
            "kind": "begin",
            "n_pages": len(pages),
            "n_chunks": len(chunks),
            # The sending instance's identity: the receive side ledgers
            # the (src, dst) link by name (docs/observability.md
            # "Fleet plane").
            "src_instance": tel.instance,
        }
        # The receiver's transfer span joins the sender's trace.
        trace = wire_headers()
        if trace:
            begin["trace"] = trace
        if lease is not None:
            begin.update(lease.to_header())
        if extra_header:
            # Caller-supplied BEGIN metadata (the reclaim plane ships
            # its block-hash chain here — docs/fault_tolerance.md).
            begin.update(extra_header)
        await write_message(writer, TwoPartMessage(MsgType.FRAME, begin))
        unacked = 0
        for idx, chunk in enumerate(chunks):
            header, payload = encode_pages(chunk)
            header.update(
                {"request_id": request_id, "kind": "data", "chunk": idx}
            )
            await write_message(
                writer, TwoPartMessage(MsgType.FRAME, header, payload)
            )
            total_bytes += len(payload)
            unacked += 1
            if unacked >= window:
                await _read_ack()  # per-chunk ack
                unacked -= 1
        while unacked > 0:
            await _read_ack()
            unacked -= 1
        await write_message(
            writer,
            TwoPartMessage(
                MsgType.FRAME, {"request_id": request_id, "kind": "end"}
            ),
        )
        # Final ack: pages are known-delivered before the prefill worker
        # releases/reuses its device pages.
        await _read_ack()
        end = time.time()
        tel.kv_transfer_duration.labels("send").observe(end - t0)
        tel.kv_transfer_bytes.labels("send").observe(total_bytes)
        tel.kv_transfer_total.labels("send", "ok").inc()
        # Per-link ledger: the sender's extract->ack view of the link.
        dst = dst_instance or return_addr
        get_transfer_ledger().record(tel.instance, dst, total_bytes, end - t0)
        tel.emit_stage(
            "kv_transfer_send",
            t0,
            end,
            current_trace(),
            request_id=request_id,
            pages=len(pages),
            bytes=total_bytes,
            src=tel.instance,
            dst=dst,
        )
    except BaseException:
        tel.kv_transfer_total.labels("send", "error").inc()
        raise
    finally:
        writer.close()
        with contextlib.suppress(Exception):
            await writer.wait_closed()


class KvPageReceiver:
    """Decode-worker side: accepts prefill results, resolves per-request
    futures. One receiver per decode worker process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._pending: dict[str, asyncio.Future] = {}
        self._chunk_cbs: dict[str, object] = {}
        # Late-claim hook for transfers nobody pre-registered: called
        # with (request_id, begin_header) and may call expect() to adopt
        # the transfer before it is dropped. The reclaim plane's
        # MigrationSink claims "migrate:*" ids here — a dying sender
        # cannot pre-announce through any channel but the wire itself.
        self.on_unclaimed = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("KV receiver closed"))
        self._pending.clear()
        self._chunk_cbs.clear()

    def expect(self, request_id: str, on_chunk=None) -> asyncio.Future:
        """Register interest *before* queueing the prefill request, so the
        result can't race past us. ``on_chunk(pages)`` (if given) fires
        per arriving DATA frame — the hook that lets a decode engine
        start injecting while later pages are still in flight; pages
        then travel ONLY through the callback (bounded receiver memory)
        and the future resolves to (first_token, []) at END."""
        fut = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        if on_chunk is not None:
            self._chunk_cbs[request_id] = on_chunk
        return fut

    def forget(self, request_id: str) -> None:
        self._pending.pop(request_id, None)
        self._chunk_cbs.pop(request_id, None)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        fut = None
        rid = ""
        try:
            msg = await read_message(reader)
            rid = msg.header.get("request_id", "")
            fut = self._pending.pop(rid, None)
            if (
                fut is None
                and self.on_unclaimed is not None
                and msg.header.get("kind") == "begin"
            ):
                with contextlib.suppress(Exception):
                    self.on_unclaimed(rid, dict(msg.header))
                fut = self._pending.pop(rid, None)
            if fut is None or fut.done():
                logger.warning("KV pages for unknown request %s dropped", rid)
                # Still drain the sender's frames so it doesn't hang on
                # acks, then ack-close.
                if msg.header.get("kind") == "begin":
                    while msg.header.get("kind") != "end":
                        await write_message(
                            writer,
                            TwoPartMessage(MsgType.COMPLETE, {"ok": True}),
                        )
                        msg = await read_message(reader)
            elif msg.msg_type == MsgType.ERROR:
                fut.set_exception(
                    RuntimeError(msg.header.get("error", "prefill failed"))
                )
            elif msg.header.get("kind") == "begin":
                begin_header = msg.header
                first_token = msg.header["first_token"]
                t0 = time.time()
                n_bytes = 0
                n_pages = 0
                trace = TraceContext.from_wire(msg.header.get("trace"))
                on_chunk = self._chunk_cbs.pop(rid, None)
                pages: list = []
                while True:
                    msg = await read_message(reader)
                    if msg.header.get("kind") == "end":
                        break
                    n_bytes += len(msg.payload or b"")
                    chunk = decode_pages(msg.header, msg.payload)
                    n_pages += len(chunk)
                    if on_chunk is not None:
                        # Streaming consumer: pages leave through the
                        # callback as they land (the receiver-side
                        # memory bound); the future carries only the
                        # first token so nothing is delivered twice.
                        on_chunk(chunk)
                    else:
                        pages.extend(chunk)
                    await write_message(
                        writer, TwoPartMessage(MsgType.COMPLETE, {"ok": True})
                    )
                fut.set_result((first_token, pages))
                end = time.time()
                tel = get_telemetry()
                tel.kv_transfer_duration.labels("recv").observe(end - t0)
                tel.kv_transfer_bytes.labels("recv").observe(n_bytes)
                tel.kv_transfer_total.labels("recv", "ok").inc()
                # Per-link ledger, receive-side view: in a real fleet
                # each process only ever sees its own side of a link, so
                # the decode worker learns inbound bandwidth without a
                # cross-instance scrape.
                src = begin_header.get("src_instance") or "?"
                get_transfer_ledger().record(
                    src, tel.instance, n_bytes, end - t0
                )
                tel.emit_stage(
                    "kv_transfer_recv",
                    t0,
                    end,
                    trace,
                    request_id=rid,
                    pages=n_pages,
                    bytes=n_bytes,
                    src=src,
                    dst=tel.instance,
                    # Which handoff lease covered this transfer (tracing
                    # orphan reclaims back to their request).
                    lease_id=begin_header.get("lease_id"),
                )
            else:
                # Unchunked single-frame transfers are rejected outright:
                # one frame would buffer the whole KV payload (hundreds of
                # MB at long ISL) in receiver memory, defeating the
                # chunked/windowed bound. A sender speaking the old shape
                # must fail visibly, not degrade silently.
                err = (
                    "unchunked KV transfer frame rejected (sender too "
                    "old: expected begin/data/end chunk protocol)"
                )
                get_telemetry().kv_transfer_total.labels("recv", "error").inc()
                fut.set_exception(RuntimeError(err))
                # The sender treats the final ack as proof of delivery
                # before releasing its device pages — it must see the
                # failure: an ERROR frame (checked by _read_ack) rather
                # than an ok-shaped COMPLETE a naive sender would take
                # as confirmation.
                await write_message(
                    writer, TwoPartMessage(MsgType.ERROR, {"error": err})
                )
                return
            await write_message(writer, TwoPartMessage(MsgType.COMPLETE, {"ok": True}))
        except (asyncio.IncompleteReadError, ConnectionError) as e:
            # A connection drop mid-transfer must fail the waiting
            # request immediately: the future was already popped from
            # _pending, so close() can no longer reach it. Count the
            # error only for a real in-flight transfer — port scanners
            # connecting and hanging up (fut None), or a post-outcome
            # write failure (fut already done), must not skew the rate.
            if fut is not None and not fut.done():
                get_telemetry().kv_transfer_total.labels("recv", "error").inc()
                fut.set_exception(
                    ConnectionError(f"KV transfer dropped mid-stream: {e}")
                )
        except Exception as e:  # noqa: BLE001 - a malformed frame must fail
            # the waiting request *now*, not leave it to time out.
            logger.exception("bad KV transfer frame")
            if fut is not None and not fut.done():
                get_telemetry().kv_transfer_total.labels("recv", "error").inc()
                fut.set_exception(RuntimeError(f"bad KV transfer frame: {e}"))
        finally:
            self._chunk_cbs.pop(rid, None)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()
