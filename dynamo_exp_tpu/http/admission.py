"""Edge admission control: bounded in-flight work with priority-aware
load shedding (docs/fault_tolerance.md "Overload protection").

The HTTP ingress accepts unboundedly without this: a traffic burst
queues behind the engine and degrades *every* request instead of
degrading gracefully. The controller keeps one in-flight count per
service (everything between admission and the final frame) and two
watermarks:

- ``shed_watermark``: above it, admission becomes priority-graduated —
  ``low`` sheds first, ``normal`` at the midpoint, ``high`` rides all
  the way to the cap. A shed request gets **429 + Retry-After** (the
  request is fine, the service is busy; retrying later will succeed).
- ``max_inflight`` (the hard cap): above it nothing is admitted — even
  ``high`` gets **503 + Retry-After**. The queue is never unbounded.

Priorities arrive as the ``priority`` extension field (request body or
``nvext``) or the ``X-Request-Priority`` header: ``low`` / ``normal`` /
``high`` or the integers 0/1/2. Unknown values are a 400, not a silent
``normal`` — a client that *tried* to prioritize deserves to know the
spelling was wrong.
"""

from __future__ import annotations

import threading

from ..protocols.common import (  # noqa: F401 - re-exported API
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    parse_priority,
    priority_name,
)
from ..telemetry import get_telemetry


class RequestShedError(Exception):
    """Admission refused for this priority class right now (HTTP 429)."""

    status = 429

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ServiceOverloadedError(RequestShedError):
    """The hard in-flight cap is reached — nothing is admitted (503)."""

    status = 503


def _resolve_watermark(max_inflight: int, shed_watermark: int | None) -> int:
    """Default high watermark: 3/4 of the cap (at least 1 so the
    graduated band exists); an explicit value is clamped to the cap.
    Shared by the constructor and :meth:`AdmissionController.resize` so
    the policy can't silently diverge between the two."""
    if shed_watermark is not None:
        return min(shed_watermark, max_inflight)
    return max((max_inflight * 3) // 4, 1)


class AdmissionController:
    """Per-service in-flight bound with priority-graduated shedding.

    Thread-safe (aiohttp handlers run on one loop, but the counter is
    also read by bench harnesses and metrics scrapes); admission is a
    single lock-guarded compare-and-increment, so the hot path costs
    nothing measurable next to a forward pass."""

    def __init__(
        self,
        max_inflight: int = 64,
        shed_watermark: int | None = None,
        retry_after_s: float = 1.0,
    ):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = max_inflight
        self.shed_watermark = _resolve_watermark(max_inflight, shed_watermark)
        self.retry_after_s = retry_after_s
        self._inflight = 0
        self._lock = threading.Lock()
        # Lifetime counters (bench + tests read these; prometheus mirrors
        # ride the telemetry registry).
        self.admitted_total = 0
        self.shed_total = 0

    @property
    def inflight(self) -> int:
        return self._inflight

    def resize(self, max_inflight: int, shed_watermark: int | None = None) -> None:
        """Move the bounds on a live controller (the cluster simulator
        and autoscaled deployments scale the admission budget with the
        fleet). In-flight work above a shrunk cap is never shed — the
        new bounds apply to future acquires only."""
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        with self._lock:
            self.max_inflight = max_inflight
            self.shed_watermark = _resolve_watermark(
                max_inflight, shed_watermark
            )

    def threshold(self, priority: int) -> int:
        """The in-flight level at which ``priority`` stops being
        admitted: ``low`` at the watermark, ``high`` at the hard cap,
        classes in between spaced linearly across the shed band."""
        band = self.max_inflight - self.shed_watermark
        frac = min(max(priority, 0), PRIORITY_HIGH) / PRIORITY_HIGH
        return self.shed_watermark + int(band * frac)

    def acquire(self, priority: int = PRIORITY_NORMAL) -> None:
        """Admit one request or raise the matching shed error.

        Every successful ``acquire`` must be paired with exactly one
        ``release`` (use :meth:`admit` for the context-manager form)."""
        tel = get_telemetry()
        with self._lock:
            if self._inflight >= self.max_inflight:
                self.shed_total += 1
                tel.requests_shed.labels(priority_name(priority), "503").inc()
                raise ServiceOverloadedError(
                    f"service at capacity ({self._inflight} in flight, "
                    f"cap {self.max_inflight})",
                    self.retry_after_s,
                )
            if self._inflight >= self.threshold(priority):
                self.shed_total += 1
                tel.requests_shed.labels(priority_name(priority), "429").inc()
                raise RequestShedError(
                    f"shedding {priority_name(priority)}-priority work "
                    f"({self._inflight} in flight, watermark "
                    f"{self.threshold(priority)})",
                    self.retry_after_s,
                )
            self._inflight += 1
            self.admitted_total += 1
            tel.admission_inflight.set(self._inflight)

    def release(self) -> None:
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1
            get_telemetry().admission_inflight.set(self._inflight)

    def admit(self, priority: int = PRIORITY_NORMAL) -> "_Admission":
        """``with admission.admit(priority): ...`` — acquire on enter
        (raising the shed error before the body runs), release on exit."""
        return _Admission(self, priority)


class _Admission:
    def __init__(self, controller: AdmissionController, priority: int):
        self._controller = controller
        self._priority = priority

    def __enter__(self) -> "_Admission":
        self._controller.acquire(self._priority)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._controller.release()
