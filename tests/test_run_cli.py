"""dynamo-run-equivalent CLI tests.

Reference capability: ``/root/reference/launch/dynamo-run/`` — one CLI
building every node shape. Covered here: arg parsing, the local batch
driver on a real tiny TPU engine, and the flagship 3-process flow
(coordinator + worker subprocess + in-proc HTTP ingress with dynamic
model discovery), including elastic model removal on worker death.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys

import pytest

from dynamo_exp_tpu.run import main_async, parse_args

from .fixtures import build_tiny_model_dir, free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_args_io_and_flags():
    opts = parse_args(
        ["in=http", "out=dyn://ns.comp.ep", "--router-mode", "kv", "--tp", "2"]
    )
    assert opts.input == "http"
    assert opts.output == "dyn://ns.comp.ep"
    assert opts.router_mode == "kv"
    assert opts.tp == 2
    # defaults
    d = parse_args([])
    assert (d.input, d.output) == ("text", "echo_full")


def test_tokenizer_registrable_probe(tmp_path):
    """Worker registration probe: fast/SP artifacts register, GPT-2-style
    vocab.json+merges.txt dirs register, weights-only dirs don't."""
    from dynamo_exp_tpu.run import tokenizer_registrable

    bare = tmp_path / "bare"
    bare.mkdir()
    (bare / "model.safetensors").write_bytes(b"")
    assert not tokenizer_registrable(str(bare))

    fast = build_tiny_model_dir(str(tmp_path / "fast"))
    assert tokenizer_registrable(fast)

    gpt2 = tmp_path / "gpt2"
    gpt2.mkdir()
    (gpt2 / "vocab.json").write_text("{}")
    (gpt2 / "merges.txt").write_text("#version: 0.2\n")
    assert tokenizer_registrable(str(gpt2))


async def test_batch_driver_on_tpu_engine(tmp_path, capsys):
    model_dir = build_tiny_model_dir(str(tmp_path / "model"))
    prompts = tmp_path / "p.jsonl"
    prompts.write_text(
        "\n".join(json.dumps({"text": t}) for t in ["hello world", "the quick fox"])
    )
    opts = parse_args(
        [
            f"in=batch:{prompts}",
            "out=tpu",
            "--model-path", model_dir,
            "--random-weights",
            "--max-tokens", "8",
            "--max-decode-slots", "2",
            "--page-size", "8",
            "--max-model-len", "128",
            "--kv-dtype", "float32",
        ]
    )
    await main_async(opts)
    out = capsys.readouterr().out.strip().splitlines()[-1]
    stats = json.loads(out)
    assert stats["requests"] == 2
    # 8 tokens per request max; random weights may sample EOS earlier.
    assert 2 <= stats["output_tokens"] <= 16
    assert stats["output_tok_s"] > 0



async def test_three_process_serve_with_discovery(tmp_path):
    """coordinator + CLI worker subprocess + CLI HTTP ingress, dynamic
    model discovery, elastic removal on worker death."""
    import aiohttp

    from dynamo_exp_tpu.runtime.transports.coordinator import CoordinatorServer

    model_dir = build_tiny_model_dir(str(tmp_path / "model"))
    server = CoordinatorServer()
    await server.start()

    env = dict(os.environ, PYTHONPATH=REPO)
    worker = subprocess.Popen(
        [
            sys.executable, "-m", "dynamo_exp_tpu.run",
            "in=dyn://t.worker.generate", "out=tpu",
            "--model-path", model_dir,
            "--model-name", "tiny",
            "--random-weights",
            "--coordinator", server.address,
            "--max-decode-slots", "2",
            "--page-size", "8",
            "--max-model-len", "128",
            "--kv-dtype", "float32",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )

    port = free_port()
    ingress_opts = parse_args(
        [
            "in=http", "out=dyn://t.worker.generate",
            "--coordinator", server.address,
            "--http-host", "127.0.0.1", "--http-port", str(port),
        ]
    )
    ingress = asyncio.ensure_future(main_async(ingress_opts))
    base = f"http://127.0.0.1:{port}"
    try:
        async with aiohttp.ClientSession() as http:
            # Wait for ingress up + worker's model discovered.
            for _ in range(600):
                if worker.poll() is not None:
                    raise AssertionError(
                        "worker died:\n" + worker.stdout.read()
                    )
                try:
                    r = await http.get(base + "/v1/models")
                    models = [m["id"] for m in (await r.json())["data"]]
                    if "tiny" in models:
                        break
                except aiohttp.ClientConnectionError:
                    pass
                await asyncio.sleep(0.25)
            else:
                raise AssertionError("model never discovered")

            r = await http.post(
                base + "/v1/chat/completions",
                json={
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "hello"}],
                    "stream": False,
                    "max_tokens": 4,
                },
            )
            assert r.status == 200, await r.text()
            data = await r.json()
            assert data["choices"][0]["message"]["content"]

            # Elastic removal: kill the worker; lease expiry must drop
            # the model from ingress. NB: poll asynchronously — a blocking
            # worker.wait() would freeze this loop, which also hosts the
            # coordinator the worker's graceful shutdown talks to.
            worker.send_signal(signal.SIGTERM)
            for _ in range(120):
                if worker.poll() is not None:
                    break
                await asyncio.sleep(0.25)
            else:
                raise AssertionError("worker did not exit on SIGTERM")
            for _ in range(240):
                r = await http.get(base + "/v1/models")
                models = [m["id"] for m in (await r.json())["data"]]
                if "tiny" not in models:
                    break
                await asyncio.sleep(0.25)
            else:
                raise AssertionError("model not removed after worker death")
    finally:
        ingress.cancel()
        try:
            await ingress
        except (asyncio.CancelledError, Exception):
            pass
        if worker.poll() is None:
            worker.kill()
            worker.wait(timeout=10)
        await server.close()


def test_profiler_trace_capture(tmp_path):
    """trace_to produces a profile artifact directory (CPU backend)."""
    import os

    from dynamo_exp_tpu.runtime.profiler import trace_to

    import jax.numpy as jnp

    with trace_to(str(tmp_path)):
        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found += [f for f in files if f.endswith((".pb", ".json.gz", ".trace"))]
    assert found, "no profiler artifacts written"
