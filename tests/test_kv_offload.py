"""G2 host-offload tier tests: HostKvPool mechanics and end-to-end
engine correctness when evicted pages come back from host RAM.

Reference capability: ``/root/reference/lib/llm/src/kv/manager.rs:22-168``
(G1/G2 tiers) and ``lib/llm/tests/kv_manager.rs`` (pool tests without
GPU); here the tiny engine runs on the virtual CPU mesh.
"""

import asyncio

import numpy as np

from dynamo_exp_tpu.engine import EngineConfig, HostKvPool, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput

PS = 8


# ---------------------------------------------------------------- unit tier
def test_host_pool_store_fetch_lru():
    pool = HostKvPool(2, page_shape=(1, 4, 1, 2), dtype=np.float32)
    k0 = np.full((1, 4, 1, 2), 1.0, np.float32)
    pool.store(100, k0, k0 * 2)
    got = pool.fetch(100)
    assert got is not None
    np.testing.assert_array_equal(got[0], k0)
    np.testing.assert_array_equal(got[1], k0 * 2)
    # Fetched copy survives the slot being recycled.
    pool.store(200, k0 * 3, k0 * 3)
    pool.store(300, k0 * 4, k0 * 4)  # evicts LRU
    np.testing.assert_array_equal(got[0], k0)
    # LRU after store(100), fetch(100), store(200), store(300) at
    # capacity 2: 100 is oldest and must be the one evicted.
    assert 100 not in pool
    assert 200 in pool and 300 in pool
    assert pool.resident == 2
    assert pool.evictions == 1


def test_host_pool_store_idempotent_per_hash():
    pool = HostKvPool(2, page_shape=(1, 2, 1, 2), dtype=np.float32)
    a = np.ones((1, 2, 1, 2), np.float32)
    pool.store(7, a, a)
    pool.store(7, a * 5, a * 5)  # refresh, not duplicate
    assert pool.resident == 1
    got = pool.fetch(7)
    np.testing.assert_array_equal(got[0], a * 5)


def test_match_chain_is_prefix_only():
    pool = HostKvPool(4, page_shape=(1, 2, 1, 2), dtype=np.float32)
    a = np.ones((1, 2, 1, 2), np.float32)
    pool.store(1, a, a)
    pool.store(3, a, a)
    assert pool.match_chain([1, 2, 3]) == [1]
    assert pool.match_chain([1, 3]) == [1, 3]
    assert pool.match_chain([2]) == []


# ---------------------------------------------------------- engine e2e tier
def offload_engine(num_pages: int, host_pages: int) -> TPUEngine:
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=num_pages,
        max_model_len=128,
        eos_token_ids=[],
        host_cache_pages=host_pages,
        kv_dtype="float32",  # bit-exact across offload round-trips
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def run_one(engine, prompt, n):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = n
    b.stop_conditions.ignore_eos = True
    stream = await engine.generate(b.to_dict())
    tokens = []
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
    return tokens


def test_offload_roundtrip_restores_evicted_prefix():
    # Pool of 8 pages: prompt A takes 4 (3 full + 1 partial); prompt B
    # needs 6, which exhausts the free list and evicts A's parked pages;
    # the A re-run then restores its prefix from the host tier.
    eng = offload_engine(num_pages=8, host_pages=32)
    eng.start()
    try:
        rs = np.random.RandomState(0)
        prompt_a = list(rs.randint(3, 200, size=3 * PS + 2))
        prompt_b = list(rs.randint(3, 200, size=5 * PS + 2))

        first = asyncio.run(run_one(eng, prompt_a, 6))
        # B needs most of the pool -> A's parked pages get evicted.
        asyncio.run(run_one(eng, prompt_b, 6))
        eng.copy_stream.drain()
        assert eng.host_pool.stores > 0  # eviction actually offloaded

        hits_before = eng.host_pool.hits
        second = asyncio.run(run_one(eng, prompt_a, 6))
        assert eng.host_pool.hits > hits_before  # prefix came from G2
        assert second == first  # and the restored KV is bit-correct
    finally:
        eng.stop()


def test_offload_disabled_by_default():
    eng = offload_engine(num_pages=10, host_pages=0)
    assert eng.host_pool is None and eng.copy_stream is None
    eng.start()
    try:
        out = asyncio.run(run_one(eng, [5, 6, 7, 8], 4))
        assert len(out) == 4
        assert "host_cache_resident" not in eng.metrics()
    finally:
        eng.stop()


def test_metrics_expose_host_tier():
    eng = offload_engine(num_pages=10, host_pages=8)
    eng.start()
    try:
        asyncio.run(run_one(eng, list(range(3, 3 + 2 * PS + 1)), 4))
        m = eng.metrics()
        assert {"host_cache_resident", "host_cache_hits", "host_cache_stores"} <= set(m)
    finally:
        eng.stop()
