from .config import EngineConfig
from .engine import TPUEngine
from .kv_manager import KvEvent, KvPageManager
from .offload import CopyStream, HostKvPool
from .scheduler import Scheduler, Sequence

__all__ = [
    "EngineConfig",
    "TPUEngine",
    "KvPageManager",
    "KvEvent",
    "HostKvPool",
    "CopyStream",
    "Scheduler",
    "Sequence",
]
