"""Service decorators and the per-process runtime context.

Reference parity: ``deploy/dynamo/sdk/lib/service.py:37-348`` (the
``@service`` class decorator + ``DynamoService``), ``decorators.py:26-90``
(``@dynamo_endpoint``, ``@async_on_start``), and the ``dynamo_context``
global populated by ``serve_dynamo.py:120-367``.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable

# Populated by serve_service before the service class is instantiated:
# {"runtime": DistributedRuntime, "component": Component, "namespace": str,
#  "endpoints": [names], "instance_ids": {endpoint: id}}
dynamo_context: dict[str, Any] = {}


@dataclass
class ServiceSpec:
    """Everything the supervisor needs to launch one service class."""

    cls: type
    name: str
    namespace: str = "dynamo"
    workers: int = 1
    # Resource request, e.g. {"tpu": 4} chips or {"cpu": "2", "memory": "2Gi"}.
    resources: dict[str, Any] = field(default_factory=dict)
    enabled: bool = True  # dynamo disabled = plain local object (reference)
    endpoints: dict[str, Callable] = field(default_factory=dict)
    on_start: list[str] = field(default_factory=list)
    # Method name marked @stats_handler: () -> dict, scraped by the
    # stats plane (planner / metrics exporter signals).
    stats_method: str | None = None

    @property
    def component_name(self) -> str:
        return self.name


def service(
    dynamo: dict | None = None,
    resources: dict | None = None,
    workers: int = 1,
    name: str | None = None,
):
    """Class decorator registering a service.

    ``@service(dynamo={"namespace": "ns"}, resources={"tpu": 1}, workers=2)``
    """

    def wrap(cls: type) -> type:
        dyn = dynamo or {}
        spec = ServiceSpec(
            cls=cls,
            name=name or cls.__name__,
            namespace=dyn.get("namespace", "dynamo"),
            workers=workers,
            resources=resources or {},
            enabled=dyn.get("enabled", True),
        )
        for attr, val in inspect.getmembers(cls):
            ep_name = getattr(val, "__dynamo_endpoint__", None)
            if ep_name is not None:
                spec.endpoints[ep_name] = val
            if getattr(val, "__dynamo_on_start__", False):
                spec.on_start.append(attr)
            if getattr(val, "__dynamo_stats__", False):
                spec.stats_method = attr
        cls.__dynamo_spec__ = spec
        return cls

    return wrap


def endpoint(name: str | None = None):
    """Mark an async-generator method as a served endpoint.

    The method signature is ``async def gen(self, request: dict)`` yielding
    response dicts; the serving layer wraps frames into the Annotated
    envelope (reference: ``@dynamo_endpoint``, ``decorators.py:26-60``).
    """

    def wrap(fn):
        fn.__dynamo_endpoint__ = name or fn.__name__
        return fn

    # Allow bare usage: @endpoint
    if callable(name):
        fn, name = name, None
        return wrap(fn)
    return wrap


def async_on_start(fn):
    """Run after the runtime context exists, before endpoints serve
    (reference: ``@async_on_start``)."""
    fn.__dynamo_on_start__ = True
    return fn


def stats_handler(fn):
    """Mark a ``def stats(self) -> dict`` method as the service's load
    report, scraped by the stats plane: the planner's KV-load signal and
    the metrics exporter both read it (reference capability: the vLLM
    worker's ``KvMetricsPublisher``, SURVEY.md §2.5)."""
    fn.__dynamo_stats__ = True
    return fn


def get_spec(cls: type) -> ServiceSpec:
    spec = getattr(cls, "__dynamo_spec__", None)
    if spec is None:
        raise TypeError(f"{cls.__name__} is not decorated with @service")
    return spec


def discover_graph(root: type) -> list[ServiceSpec]:
    """The dependency closure of ``root``, dependencies first.

    Reference: graphs link services via ``depends()`` class attributes
    (``examples/llm/graphs/agg.py``); the serve CLI launches every
    service in the closure.
    """
    from .dependency import depends as _depends

    order: list[ServiceSpec] = []
    seen: set[type] = set()

    def visit(cls: type) -> None:
        if cls in seen:
            return
        seen.add(cls)
        for dep in vars(cls).values():
            if isinstance(dep, _depends):
                visit(dep.target)
        order.append(get_spec(cls))

    visit(root)
    return order
