"""KV-router wire types.

Capability parity with ``/root/reference/lib/llm/src/kv_router/protocols.rs``:
``ForwardPassMetrics`` (:43-55), ``KvCacheEvent`` Stored/Removed (:79-127),
``RouterEvent`` envelope, and the router request/response.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any

from ..tokens import HASH_ALGO_VERSION

logger = logging.getLogger(__name__)

# Hash-algorithm versions we've already warned about (once per version,
# not per event — the event plane carries thousands of these).
_warned_hash_versions: set[int] = set()


@dataclass
class ForwardPassMetrics:
    """Worker load snapshot published via the stats plane."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0

    def to_dict(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_dict(cls, d: dict) -> "ForwardPassMetrics":
        known = {k: d[k] for k in cls().__dict__ if k in d}
        return cls(**known)


@dataclass
class KvCacheStoredBlock:
    block_hash: int  # chained sequence hash
    tokens: list[int] | None = None


@dataclass
class KvCacheEventData:
    """One stored/removed notification from a worker's page manager."""

    kind: str  # "stored" | "removed"
    block_hashes: list[int] = field(default_factory=list)
    parent_hash: int | None = None


@dataclass
class RouterEvent:
    """Event envelope attributed to a worker (reference: RouterEvent)."""

    worker_id: int
    data: KvCacheEventData
    hash_version: int = HASH_ALGO_VERSION

    def to_dict(self) -> dict:
        return {
            "worker_id": self.worker_id,
            "kind": self.data.kind,
            "block_hashes": list(self.data.block_hashes),
            "parent_hash": self.data.parent_hash,
            "hash_version": self.hash_version,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RouterEvent":
        # A worker on a different block-hash algorithm produces hashes
        # the local indexer can never match: surface the mismatch once
        # instead of silently losing KV-aware routing mid-rollout.
        version = int(d.get("hash_version", 1))
        if version != HASH_ALGO_VERSION and version not in _warned_hash_versions:
            _warned_hash_versions.add(version)
            logger.warning(
                "KV event from worker %s uses block-hash algorithm v%d "
                "(local: v%d) — prefix reuse across this pair is disabled "
                "until versions match",
                d.get("worker_id"),
                version,
                HASH_ALGO_VERSION,
            )
        return cls(
            worker_id=int(d["worker_id"]),
            data=KvCacheEventData(
                kind=d["kind"],
                block_hashes=[int(h) for h in d.get("block_hashes", [])],
                parent_hash=d.get("parent_hash"),
            ),
            hash_version=version,
        )


@dataclass
class OverlapScores:
    """find_matches result: per-worker contiguous matched-prefix blocks."""

    scores: dict[int, int] = field(default_factory=dict)

    def best(self) -> int:
        return max(self.scores.values(), default=0)


@dataclass
class KVHitRateEvent:
    """Emitted per routing decision (reference: ``scheduler.rs:32``)."""

    worker_id: int
    isl_blocks: int
    overlap_blocks: int

    def to_dict(self) -> dict:
        return self.__dict__.copy()


# Event-plane subjects (reference: kv_router.rs:52-53).
def kv_events_subject(component_path: str) -> str:
    return f"{component_path}.kv_events"


KV_HIT_RATE_SUBJECT = "kv-hit-rate"


@dataclass
class RouterRequest:
    token_ids: list[int]

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RouterRequest":
        return cls(token_ids=list(d.get("token_ids", [])))


@dataclass
class RouterResponse:
    worker_id: int
    overlap_blocks: int = 0

    def to_dict(self) -> dict:
        return {"worker_id": self.worker_id, "overlap_blocks": self.overlap_blocks}
