"""Flight-recorder demo (``make flight``): run a tiny engine, SIGUSR1
it, render the dump.

Walks the full operator path from docs/observability.md "Engine flight
recorder & watchdog" in one process:

1. build + start a TINY CPU engine (flight ring on, explicit dump path),
2. serve a couple of requests so the ring has admission / dispatch /
   consume / finish events,
3. install the SIGUSR1 handler and send the signal to ourselves — the
   same trigger an operator uses on a wedged production worker,
4. render the dump with the ``llmctl flight`` code path.

Usage: ``JAX_PLATFORMS=cpu python examples/flight_demo.py [dump_path]``
"""

from __future__ import annotations

import asyncio
import os
import signal
import sys
import time

# Runnable straight from a checkout: `python examples/flight_demo.py`.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    dump_path = (
        sys.argv[1]
        if len(sys.argv) > 1
        else "/tmp/dynamo_flight_demo.jsonl"
    )
    if os.path.exists(dump_path):
        os.remove(dump_path)

    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.llmctl import main as llmctl_main
    from dynamo_exp_tpu.models import TINY
    from dynamo_exp_tpu.parallel import single_device_mesh
    from dynamo_exp_tpu.protocols.common import BackendInput
    from dynamo_exp_tpu.telemetry.flight import install_sigusr1

    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=2,
        page_size=8,
        num_pages=64,
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
        decode_window=4,
        flight_dump_path=dump_path,
    )
    engine = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    engine.start()

    async def serve() -> int:
        async def one(start: int) -> int:
            b = BackendInput(token_ids=list(range(start, start + 16)))
            b.stop_conditions.max_tokens = 10
            b.stop_conditions.ignore_eos = True
            stream = await engine.generate(b.to_dict())
            n = 0
            async for item in stream:
                n += len(item.get("token_ids", []))
            return n

        totals = await asyncio.gather(one(20), one(60))
        return sum(totals)

    print("# serving 2 requests on a TINY engine...", file=sys.stderr)
    tokens = asyncio.run(serve())
    print(f"# generated {tokens} tokens; sending SIGUSR1", file=sys.stderr)

    assert install_sigusr1(), "SIGUSR1 unavailable on this platform"
    os.kill(os.getpid(), signal.SIGUSR1)
    deadline = time.monotonic() + 5
    while not os.path.exists(dump_path) and time.monotonic() < deadline:
        time.sleep(0.05)
    engine.stop()
    if not os.path.exists(dump_path):
        print("no flight dump appeared", file=sys.stderr)
        return 1

    print(f"# rendering {dump_path} via `llmctl flight`:", file=sys.stderr)
    return llmctl_main(["flight", dump_path])


if __name__ == "__main__":
    sys.exit(main())
