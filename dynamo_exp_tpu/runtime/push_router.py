"""PushRouter: policy-based dispatch over a Client's live instances.

Capability parity with
``/root/reference/lib/runtime/src/pipeline/network/egress/push_router.rs``:
random / round-robin / direct(instance) / static routing, presented as an
AsyncEngine so routers compose with pipelines. KV-aware routing lives in
:mod:`dynamo_exp_tpu.router` and plugs in via ``RouterMode.DIRECT``.
"""

from __future__ import annotations

import enum
import itertools
import random
from typing import Any, AsyncIterator

from .client import Client
from .engine import AsyncEngine, AsyncEngineContext, ResponseStream
from .transports.base import InstanceInfo


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round-robin"
    DIRECT = "direct"
    STATIC = "static"
    KV = "kv"


class NoInstancesError(ConnectionError):
    pass


class PushRouter(AsyncEngine[dict, Any]):
    """Routes each request to one live instance of a remote endpoint."""

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.RANDOM,
        ready_wait_s: float = 0.0,
    ):
        self.client = client
        self.mode = mode
        # >0: a request arriving before any instance is discovered waits
        # this long for one instead of failing (ingress/graph startup
        # races); 0 keeps the strict fail-fast default.
        self.ready_wait_s = ready_wait_s
        self._rr = itertools.count()

    def _pick(self, request: dict) -> InstanceInfo:
        instances = self.client.instances
        if not instances:
            raise NoInstancesError("no live instances for endpoint")
        # An explicit target always wins, regardless of mode.
        if "_worker_instance_id" in request:
            try:
                return self.client.instance(int(request["_worker_instance_id"]))
            except KeyError as e:
                # Stale target (lease expired) is a routing error, so callers
                # can retry/503 with one except clause.
                raise NoInstancesError(str(e)) from e
        if self.mode is RouterMode.RANDOM:
            return random.choice(instances)
        if self.mode is RouterMode.ROUND_ROBIN:
            return instances[next(self._rr) % len(instances)]
        if self.mode in (RouterMode.DIRECT, RouterMode.KV):
            # The explicit-target branch above handles present ids.
            raise ValueError("direct routing requires _worker_instance_id")
        # STATIC: single fixed instance
        return instances[0]

    async def generate(
        self, request: dict, context: AsyncEngineContext | None = None
    ) -> ResponseStream[Any]:
        ctx = context or AsyncEngineContext()
        if not self.client.instances and self.ready_wait_s > 0:
            try:
                await self.client.wait_for_instances(1, self.ready_wait_s)
            except TimeoutError:
                pass  # fall through to the strict error below
        instance = self._pick(request)
        request = {k: v for k, v in request.items() if k != "_worker_instance_id"}
        frames = await self.client.generate_to(instance, request, ctx)

        async def _data() -> AsyncIterator[Any]:
            async for ann in frames:
                if ann.data is not None:
                    yield ann.data

        return ResponseStream(_data(), ctx)

    async def generate_direct(
        self,
        request: dict,
        instance_id: int,
        context: AsyncEngineContext | None = None,
    ) -> ResponseStream[Any]:
        return await self.generate(
            {**request, "_worker_instance_id": instance_id}, context
        )
