"""Frontend: the OpenAI HTTP ingress of the flagship graphs.

Reference parity: ``/root/reference/examples/llm/components/frontend.py``
(HTTP server bound to the Processor). The aiohttp OpenAI service runs
inside this service's process; chat/completion requests forward to the
Processor over the request plane and stream back as SSE.
"""

from __future__ import annotations

import logging

from dynamo_exp_tpu.sdk import (
    async_on_start,
    depends,
    dynamo_context,
    service,
)

from .processor import Processor

logger = logging.getLogger(__name__)


class _RemoteOpenAIEngine:
    """AsyncEngine adapter: OpenAI request dict → the Processor's
    ``generate`` endpoint → OpenAI chunk stream."""

    def __init__(self, dep):
        self.dep = dep

    async def generate(self, request: dict, context=None):
        from dynamo_exp_tpu.protocols.openai import (
            ChatCompletionChunk,
            CompletionChunk,
        )
        from dynamo_exp_tpu.runtime.engine import (
            AsyncEngineContext,
            ResponseStream,
        )

        ctx = context or AsyncEngineContext()
        stream = await self.dep.generate({"request": request})

        async def gen():
            # The HTTP layer streams pydantic objects (model_dump at the
            # SSE boundary); revalidate the Processor's wire dicts.
            async for chunk in stream:
                cls = (
                    ChatCompletionChunk
                    if chunk.get("object") == "chat.completion.chunk"
                    else CompletionChunk
                )
                yield cls.model_validate(chunk)

        return ResponseStream(gen(), ctx)


@service(dynamo={"namespace": "dynamo"})
class Frontend:
    processor = depends(Processor)

    served_model_name: str = "model"
    port: int = 8000
    host: str = "0.0.0.0"

    def __init__(self):
        self.service = None

    @async_on_start
    async def start_http(self) -> None:
        from dynamo_exp_tpu.http import HttpService

        self.service = HttpService(host=self.host, port=self.port)
        engine = _RemoteOpenAIEngine(self.processor)
        self.service.manager.add_chat_model(self.served_model_name, engine)
        self.service.manager.add_completion_model(
            self.served_model_name, engine
        )
        port = await self.service.start()
        logger.info("frontend listening on %s:%d", self.host, port)
        print(f"frontend on http://{self.host}:{port}", flush=True)
