"""Request templates: server-side defaults for incoming OpenAI requests.

Capability parity with ``/root/reference/lib/llm/src/request_template.rs``
(+ its application in ``launch/dynamo-run``'s HTTP input): a JSON file
of defaults (model, temperature, max_completion_tokens) applied to any
request that leaves those fields unset, so clients can POST minimal
bodies against a curated deployment.
"""

from __future__ import annotations

import json
from dataclasses import dataclass


@dataclass
class RequestTemplate:
    model: str = ""
    temperature: float | None = None
    max_completion_tokens: int | None = None

    @classmethod
    def load(cls, path: str) -> "RequestTemplate":
        with open(path) as f:
            data = json.load(f)
        return cls(
            model=data.get("model", ""),
            temperature=data.get("temperature"),
            max_completion_tokens=data.get("max_completion_tokens"),
        )

    def apply(self, request: dict) -> dict:
        """Fill unset fields in an OpenAI request dict (in place +
        returned). Explicit client values always win."""
        if self.model and not request.get("model"):
            request["model"] = self.model
        if self.temperature is not None and request.get("temperature") is None:
            request["temperature"] = self.temperature
        if self.max_completion_tokens is not None:
            if (
                request.get("max_tokens") is None
                and request.get("max_completion_tokens") is None
            ):
                request["max_completion_tokens"] = self.max_completion_tokens
        return request
