"""Mixture-of-experts FFN: exact (dropless) top-k routing on TPU.

The reference delegates MoE models (Mixtral, DeepSeek) to the engines it
wraps (SURVEY.md §2.10 "Expert parallel / MoE: delegated to engines",
vLLM patch DeepSeek MLA hooks). Here the TPU engine owns the model, so
MoE is a first-class op.

TPU-first design:

- **Sorted dispatch + ``jax.lax.ragged_dot``**: tokens are replicated
  k ways, sorted by expert id, and each expert's contiguous group runs
  through a grouped matmul (MegaBlocks-style, but using XLA's native
  ragged_dot so Mosaic picks the tiling). Exact — no capacity factor,
  no dropped tokens, unlike the classic dispatch-einsum formulation.
- **float32 router**: routing logits/softmax in float32; a bf16 router
  flips top-k selections near ties and decodes diverge run-to-run.
- **Sharding**: expert weights carry ``P(None, None, tp)`` specs —
  every expert's FFN is tensor-parallel over the same ``tp`` axis as
  the dense path, so MoE composes with the existing GSPMD layout and
  XLA inserts the psum after ``w_down``. (Expert parallelism — experts
  sharded over their own mesh axis — is a layout change on the same
  weights; for inference the tp-within-expert layout keeps every chip
  busy regardless of routing skew.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def moe_router(
    x: jnp.ndarray,  # [N, D]
    router_w: jnp.ndarray,  # [D, E]
    num_experts_per_tok: int,
    norm_topk_prob: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k routing. Returns (weights [N, K] float32, expert ids [N, K]).

    Softmax over ALL experts first, then top-k (Mixtral order); with
    ``norm_topk_prob`` the selected weights are renormalised to sum to 1.
    """
    logits = (x.astype(jnp.float32) @ router_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    weights, ids = jax.lax.top_k(probs, num_experts_per_tok)
    if norm_topk_prob:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    return weights, ids.astype(jnp.int32)


def moe_ffn(
    x: jnp.ndarray,  # [N, D] tokens (flattened batch*seq)
    router_w: jnp.ndarray,  # [D, E]
    w_gate: jnp.ndarray,  # [E, D, I]
    w_up: jnp.ndarray,  # [E, D, I]
    w_down: jnp.ndarray,  # [E, I, D]
    num_experts_per_tok: int,
    norm_topk_prob: bool = True,
) -> jnp.ndarray:
    """SwiGLU expert FFN with exact top-k dispatch. Returns [N, D].

    Every (token, selected expert) pair is computed — the sort groups
    pairs by expert so each expert sees one contiguous slab, and
    ``ragged_dot`` runs the per-group matmuls without materialising a
    one-hot dispatch tensor or imposing a capacity.
    """
    N, D = x.shape
    E = router_w.shape[-1]
    K = num_experts_per_tok
    weights, ids = moe_router(x, router_w, K, norm_topk_prob)

    flat_ids = ids.reshape(-1)  # [N*K]
    # Stable sort so each token's k replicas keep a deterministic order.
    order = jnp.argsort(flat_ids, stable=True)  # [N*K]
    token_of = order // K  # originating token per sorted row
    xs = jnp.take(x, token_of, axis=0)  # [N*K, D] in expert order
    group_sizes = jnp.bincount(flat_ids, length=E)

    g = jax.lax.ragged_dot(xs, w_gate, group_sizes)
    u = jax.lax.ragged_dot(xs, w_up, group_sizes)
    h = (jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u)
    out = jax.lax.ragged_dot(h, w_down, group_sizes)  # [N*K, D]

    w_sorted = jnp.take(weights.reshape(-1), order)  # [N*K] float32
    out = out.astype(jnp.float32) * w_sorted[:, None]
    # Unsort + combine: scatter-add each replica back onto its token.
    y = jnp.zeros((N, D), jnp.float32).at[token_of].add(out)
    return y.astype(x.dtype)


def moe_ffn_ep(
    x: jnp.ndarray,  # [N, D] (replicated across the moe axes)
    router_w: jnp.ndarray,  # [D, E] replicated
    w_gate: jnp.ndarray,  # [E, D, I] sharded P(ep, None, tp)
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,  # [E, I, D] sharded P(ep, tp, None)
    num_experts_per_tok: int,
    norm_topk_prob: bool,
    mesh,
    ep_axis: str = "ep",
    tp_axis: str = "tp",
) -> jnp.ndarray:
    """Expert-parallel MoE FFN: experts sharded over the mesh's ``ep``
    axis (composing with ``tp`` inside each expert), tokens replicated.

    Every rank densely computes its E/ep local experts for all tokens
    masked by the combine weights, then a psum over (ep, tp) sums the
    expert contributions and the ffn partials. Inference-shaped N makes
    the E_local× overcompute cheap relative to moving tokens between
    ranks (the training-style all-to-all dispatch), and no routing skew
    can idle a rank. SURVEY.md §2.10: "Expert parallel / MoE → mesh
    ``expert`` axis in JAX engine".
    """
    from functools import partial as _partial

    from ..parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P

    N, D = x.shape
    E = router_w.shape[-1]
    weights, ids = moe_router(x, router_w, num_experts_per_tok, norm_topk_prob)
    combine = jnp.zeros((N, E), jnp.float32)
    combine = combine.at[jnp.arange(N)[:, None], ids].add(weights)

    @_partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(),
            P(None, ep_axis),
            P(ep_axis, None, tp_axis),
            P(ep_axis, None, tp_axis),
            P(ep_axis, tp_axis, None),
        ),
        out_specs=P(),
        check_vma=False,
    )
    def f(x_l, comb_l, wg, wu, wd):
        g = jnp.einsum("nd,edi->eni", x_l, wg)
        u = jnp.einsum("nd,edi->eni", x_l, wu)
        h = jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u
        o = jnp.einsum("eni,eid->end", h, wd).astype(jnp.float32)
        y = jnp.einsum("ne,end->nd", comb_l, o)
        # Sum expert contributions (ep) and ffn partials (tp) together.
        return jax.lax.psum(y, (ep_axis, tp_axis))

    return f(x, combine, w_gate, w_up, w_down).astype(x.dtype)


def moe_ffn_reference(
    x, router_w, w_gate, w_up, w_down, num_experts_per_tok,
    norm_topk_prob=True,
):
    """Dense oracle: every expert computes every token, combine masks the
    unselected ones. O(E·N) FLOPs — tests only."""
    N, D = x.shape
    E = router_w.shape[-1]
    weights, ids = moe_router(x, router_w, num_experts_per_tok, norm_topk_prob)
    combine = jnp.zeros((N, E), jnp.float32)
    combine = combine.at[jnp.arange(N)[:, None], ids].add(weights)
    g = jnp.einsum("nd,edi->eni", x, w_gate)
    u = jnp.einsum("nd,edi->eni", x, w_up)
    h = jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u
    out = jnp.einsum("eni,eid->end", h, w_down)  # [E, N, D]
    y = jnp.einsum("ne,end->nd", combine, out.astype(jnp.float32))
    return y.astype(x.dtype)
