"""Tests for OpenAI protocol types, SSE codec, delta generation, aggregation."""

import pytest

from dynamo_exp_tpu.protocols import (
    ChatCompletionRequest,
    ChatDeltaGenerator,
    CompletionRequest,
    FinishReason,
    SseDecoder,
    aggregate_chat_stream,
    encode_done,
    encode_frame,
)
from dynamo_exp_tpu.runtime.annotated import Annotated


def test_chat_request_stop_and_sampling_extraction():
    req = ChatCompletionRequest.model_validate(
        {
            "model": "m",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 7,
            "stop": "END",
            "temperature": 0.5,
            "top_p": 0.9,
            "nvext": {"ignore_eos": True, "annotations": ["ttft"]},
        }
    )
    stop = req.extract_stop_conditions()
    assert stop.max_tokens == 7
    assert stop.stop == ["END"]
    assert stop.ignore_eos is True
    sampling = req.extract_sampling_options()
    assert sampling.temperature == 0.5 and sampling.top_p == 0.9
    assert req.annotations() == ["ttft"]


def test_completion_request_token_prompt():
    req = CompletionRequest.model_validate({"model": "m", "prompt": [1, 2, 3]})
    assert req.prompt == [1, 2, 3]


def test_multimodal_content_parts_text():
    req = ChatCompletionRequest.model_validate(
        {
            "model": "m",
            "messages": [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "look at "},
                        {"type": "text", "text": "this"},
                    ],
                }
            ],
        }
    )
    assert req.messages[0].text_content() == "look at this"


def test_sse_roundtrip():
    frames = [
        Annotated.from_data({"x": 1}),
        Annotated.from_error("bad thing"),
        Annotated(data={"y": 2}, event="annotation", comment=["note"]),
    ]
    wire = "".join(encode_frame(f) for f in frames) + encode_done()
    decoder = SseDecoder()
    out = list(decoder.feed(wire))
    assert out[0].data == {"x": 1}
    assert out[1].is_error() and out[1].error_message() == "bad thing"
    assert out[2].event == "annotation" and out[2].comment == ["note"]
    assert out[3].data == "[DONE]"


def test_sse_incremental_chunks():
    frame = encode_frame(Annotated.from_data({"long": "x" * 100}))
    decoder = SseDecoder()
    out = []
    for i in range(0, len(frame), 7):
        out.extend(decoder.feed(frame[i : i + 7]))
    assert len(out) == 1 and out[0].data == {"long": "x" * 100}


@pytest.mark.asyncio
async def test_delta_and_aggregation_roundtrip():
    gen = ChatDeltaGenerator("model-x")
    chunks = [
        gen.text_chunk("Hello "),
        gen.text_chunk("world"),
        gen.finish_chunk(FinishReason.EOS),
        gen.usage_chunk(10, 2),
    ]

    async def _stream():
        for c in chunks:
            yield c

    full = await aggregate_chat_stream(_stream())
    assert full.choices[0].message.content == "Hello world"
    assert full.choices[0].message.role == "assistant"
    assert full.choices[0].finish_reason == "stop"
    assert full.usage.total_tokens == 12
    assert full.id == gen.id
