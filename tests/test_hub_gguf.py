"""HF-hub resolution + GGUF checkpoint tests.

Reference capability anchors: ``lib/llm/src/hub.rs:23-84`` (hub fetch →
cache dir) and ``lib/llm/src/gguf.rs`` (GGUF metadata/content reader).
Hub tests run fully offline against a hand-built cache; GGUF tests
round-trip through our writer and cross-check the loaded params against
the safetensors loader's layout via a forward pass.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.models import TINY, forward, init_kv_cache, init_params
from dynamo_exp_tpu.models.gguf import (
    GGUFFile,
    config_from_gguf,
    load_params_from_gguf,
    write_gguf,
)
from dynamo_exp_tpu.models.hub import looks_like_hub_id, resolve_model_path


# --------------------------------------------------------------------- hub
def test_looks_like_hub_id():
    assert looks_like_hub_id("org/model")
    assert not looks_like_hub_id("/tmp")
    assert not looks_like_hub_id("model-only")
    assert not looks_like_hub_id("a/b/c")
    assert not looks_like_hub_id("./relative/path")


def test_resolve_local_dir_and_gguf_passthrough(tmp_path):
    d = tmp_path / "m"
    d.mkdir()
    assert resolve_model_path(str(d)) == str(d)
    g = tmp_path / "w.gguf"
    g.write_bytes(b"GGUF")
    assert resolve_model_path(str(g)) == str(g)


def test_resolve_rejects_garbage():
    with pytest.raises(FileNotFoundError, match="neither a local path"):
        resolve_model_path("not-a-model-or-path")


def test_resolve_hub_id_from_offline_cache(tmp_path, monkeypatch):
    """A pre-seeded HF cache resolves with zero network (the air-gapped
    TPU pod case)."""
    rev = "0123456789abcdef0123456789abcdef01234567"
    repo = tmp_path / "hub" / "models--test-org--tiny-model"
    snap = repo / "snapshots" / rev
    snap.mkdir(parents=True)
    (repo / "refs").mkdir()
    (repo / "refs" / "main").write_text(rev)
    (snap / "config.json").write_text("{}")
    monkeypatch.setenv("HF_HOME", str(tmp_path))
    monkeypatch.setenv("HF_HUB_OFFLINE", "1")  # hard-disable network
    got = resolve_model_path("test-org/tiny-model")
    assert got == str(snap)
    assert os.path.exists(os.path.join(got, "config.json"))


# -------------------------------------------------------------------- GGUF
def _tiny_gguf(path: str, cfg, params) -> None:
    """Serialize our TINY params the way llama.cpp lays a llama GGUF
    out: torch [out, in] weights (transposed from our x@W layout), q/k
    rope-permuted."""
    hd = cfg.head_dim_

    def permute(w_hf: np.ndarray, heads: int) -> np.ndarray:
        out, inner = w_hf.shape
        return (
            w_hf.reshape(heads, 2, hd // 2, inner)
            .swapaxes(1, 2)
            .reshape(out, inner)
        )

    f32 = lambda x: np.asarray(x, np.float32)  # noqa: E731
    lp = params["layers"]
    tensors = {"token_embd.weight": f32(params["embed"])}
    for i in range(cfg.num_layers):
        p = f"blk.{i}."
        tensors[p + "attn_norm.weight"] = f32(lp["attn_norm"][i])
        tensors[p + "attn_q.weight"] = permute(
            f32(lp["wq"][i]).T, cfg.num_heads
        )
        tensors[p + "attn_k.weight"] = permute(
            f32(lp["wk"][i]).T, cfg.num_kv_heads
        )
        tensors[p + "attn_v.weight"] = f32(lp["wv"][i]).T
        tensors[p + "attn_output.weight"] = f32(lp["wo"][i]).T
        tensors[p + "ffn_norm.weight"] = f32(lp["mlp_norm"][i])
        tensors[p + "ffn_gate.weight"] = f32(lp["w_gate"][i]).T
        tensors[p + "ffn_up.weight"] = f32(lp["w_up"][i]).T
        tensors[p + "ffn_down.weight"] = f32(lp["w_down"][i]).T
    tensors["output_norm.weight"] = f32(params["final_norm"])
    if "lm_head" in params:
        tensors["output.weight"] = f32(params["lm_head"]).T
    metadata = {
        "general.architecture": "llama",
        "llama.embedding_length": cfg.hidden_size,
        "llama.block_count": cfg.num_layers,
        "llama.attention.head_count": cfg.num_heads,
        "llama.attention.head_count_kv": cfg.num_kv_heads,
        "llama.feed_forward_length": cfg.intermediate_size,
        "llama.rope.dimension_count": hd,
        "llama.rope.freq_base": cfg.rope_theta,
        "llama.attention.layer_norm_rms_epsilon": cfg.rms_norm_eps,
        "llama.context_length": cfg.max_position_embeddings,
        "llama.vocab_size": cfg.vocab_size,
    }
    write_gguf(path, metadata, tensors)


def test_gguf_metadata_roundtrip(tmp_path):
    path = str(tmp_path / "t.gguf")
    write_gguf(
        path,
        {"general.architecture": "llama", "llama.block_count": 2,
         "flag": True, "name": "x", "arr": [1, 2, 3]},
        {"w": np.arange(12, dtype=np.float32).reshape(3, 4)},
    )
    g = GGUFFile.parse(path)
    assert g.metadata["general.architecture"] == "llama"
    assert g.metadata["flag"] is True
    assert g.metadata["arr"] == [1, 2, 3]
    np.testing.assert_array_equal(
        g.tensor("w"), np.arange(12, dtype=np.float32).reshape(3, 4)
    )
    assert g.tensors["w"].dims == (4, 3)  # ne order: fastest first


def test_gguf_config_and_params_match_source_model(tmp_path):
    """Write TINY through the GGUF container, load it back, and require
    bit-identical logits vs the source params — proves the dims
    convention, transposes, and rope unpermute are all inverses."""
    import dataclasses

    cfg = dataclasses.replace(TINY, dtype="float32")
    params = init_params(jax.random.PRNGKey(3), cfg)
    path = str(tmp_path / "tiny.gguf")
    _tiny_gguf(path, cfg, params)

    got_cfg = config_from_gguf(GGUFFile.parse(path))
    assert got_cfg.hidden_size == cfg.hidden_size
    assert got_cfg.num_kv_heads == cfg.num_kv_heads
    assert got_cfg.tie_word_embeddings == cfg.tie_word_embeddings

    loaded, _ = load_params_from_gguf(path, cfg)
    toks = jnp.asarray([[5, 9, 2, 7, 11, 3, 1, 8]], jnp.int32)
    pos = jnp.arange(8, dtype=jnp.int32)[None]
    table = jnp.asarray([[1, 2]], jnp.int32)

    def logits(p):
        k, v = init_kv_cache(cfg, num_pages=4, page_size=8, dtype=jnp.float32)
        out, _, _ = forward(p, cfg, toks, pos, table, k, v)
        return np.asarray(out)

    np.testing.assert_allclose(logits(loaded), logits(params), atol=2e-5)


def test_gguf_q8_0_dequant(tmp_path):
    """Hand-build a Q8_0 tensor blob and check dequantization."""
    import struct

    rs = np.random.RandomState(0)
    vals = (rs.randint(-127, 128, size=64)).astype(np.int8)
    scales = np.asarray([0.5, 0.25], np.float16)
    blob = b""
    for b in range(2):
        blob += struct.pack("<e", float(scales[b]))
        blob += vals[b * 32 : (b + 1) * 32].tobytes()
    # Minimal handcrafted GGUF container around the Q8_0 blob.
    head = bytearray()
    head += b"GGUF" + struct.pack("<IQQ", 3, 1, 0)
    name = b"q"
    head += struct.pack("<Q", len(name)) + name
    head += struct.pack("<I", 1) + struct.pack("<Q", 64)
    head += struct.pack("<I", 8)  # Q8_0
    head += struct.pack("<Q", 0)
    pad = (-len(head)) % 32
    head += b"\0" * pad
    path = tmp_path / "q.gguf"
    path.write_bytes(bytes(head) + blob)
    g = GGUFFile.parse(str(path))
    want = vals.astype(np.float32) * np.repeat(
        scales.astype(np.float32), 32
    )
    np.testing.assert_allclose(g.tensor("q"), want, rtol=1e-3)


def test_gguf_rejects_unknown_quant(tmp_path):
    import struct

    head = bytearray()
    head += b"GGUF" + struct.pack("<IQQ", 3, 1, 0)
    head += struct.pack("<Q", 1) + b"w"
    head += struct.pack("<I", 1) + struct.pack("<Q", 32)
    head += struct.pack("<I", 2)  # Q4_0: unsupported
    head += struct.pack("<Q", 0)
    head += b"\0" * ((-len(head)) % 32) + b"\0" * 64
    path = tmp_path / "bad.gguf"
    path.write_bytes(bytes(head))
    with pytest.raises(ValueError, match="unsupported GGUF encoding"):
        GGUFFile.parse(str(path)).tensor("w")


def test_gguf_moe_roundtrip(tmp_path):
    """Mixtral-style GGUF (llama arch + expert_count + stacked _exps
    tensors) loads to bit-identical logits vs the source MoE params."""
    import dataclasses

    from dynamo_exp_tpu.models import TINY_MOE

    cfg = dataclasses.replace(TINY_MOE, dtype="float32")
    params = init_params(jax.random.PRNGKey(5), cfg)
    hd = cfg.head_dim_

    def permute(w_hf, heads):
        out, inner = w_hf.shape
        return (
            w_hf.reshape(heads, 2, hd // 2, inner).swapaxes(1, 2)
            .reshape(out, inner)
        )

    f32 = lambda x: np.asarray(x, np.float32)  # noqa: E731
    lp = params["layers"]
    tensors = {"token_embd.weight": f32(params["embed"])}
    for i in range(cfg.num_layers):
        p = f"blk.{i}."
        tensors[p + "attn_norm.weight"] = f32(lp["attn_norm"][i])
        tensors[p + "attn_q.weight"] = permute(f32(lp["wq"][i]).T, cfg.num_heads)
        tensors[p + "attn_k.weight"] = permute(f32(lp["wk"][i]).T, cfg.num_kv_heads)
        tensors[p + "attn_v.weight"] = f32(lp["wv"][i]).T
        tensors[p + "attn_output.weight"] = f32(lp["wo"][i]).T
        tensors[p + "ffn_norm.weight"] = f32(lp["mlp_norm"][i])
        tensors[p + "ffn_gate_inp.weight"] = f32(lp["router"][i]).T
        # llama.cpp layout: [E, I, D] for gate/up, [E, D, I] for down.
        tensors[p + "ffn_gate_exps.weight"] = f32(lp["w_gate"][i]).swapaxes(1, 2)
        tensors[p + "ffn_up_exps.weight"] = f32(lp["w_up"][i]).swapaxes(1, 2)
        tensors[p + "ffn_down_exps.weight"] = f32(lp["w_down"][i]).swapaxes(1, 2)
    tensors["output_norm.weight"] = f32(params["final_norm"])
    if "lm_head" in params:
        tensors["output.weight"] = f32(params["lm_head"]).T
    write_gguf(
        str(tmp_path / "moe.gguf"),
        {
            "general.architecture": "llama",
            "llama.embedding_length": cfg.hidden_size,
            "llama.block_count": cfg.num_layers,
            "llama.attention.head_count": cfg.num_heads,
            "llama.attention.head_count_kv": cfg.num_kv_heads,
            "llama.feed_forward_length": cfg.intermediate_size,
            "llama.rope.dimension_count": hd,
            "llama.expert_count": cfg.num_experts,
            "llama.expert_used_count": cfg.num_experts_per_tok,
            "llama.vocab_size": cfg.vocab_size,
        },
        tensors,
    )

    got_cfg = config_from_gguf(GGUFFile.parse(str(tmp_path / "moe.gguf")))
    assert got_cfg.num_experts == cfg.num_experts
    assert got_cfg.num_experts_per_tok == cfg.num_experts_per_tok

    loaded, _ = load_params_from_gguf(str(tmp_path / "moe.gguf"), cfg)
    toks = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
    pos = jnp.arange(4, dtype=jnp.int32)[None]
    table = jnp.asarray([[1]], jnp.int32)

    def logits(p):
        k, v = init_kv_cache(cfg, num_pages=4, page_size=8, dtype=jnp.float32)
        out, _, _ = forward(p, cfg, toks, pos, table, k, v)
        return np.asarray(out)

    np.testing.assert_array_equal(logits(loaded), logits(params))


def test_gguf_qwen3_head_dim_from_key_length(tmp_path):
    """qwen3 GGUFs carry head_dim as attention.key_length (no
    rope.dimension_count); a 2560/32-head file must resolve hd=128,
    not 80."""
    write_gguf(
        str(tmp_path / "q3.gguf"),
        {
            "general.architecture": "qwen3",
            "qwen3.embedding_length": 2560,
            "qwen3.block_count": 1,
            "qwen3.attention.head_count": 32,
            "qwen3.attention.head_count_kv": 8,
            "qwen3.attention.key_length": 128,
            "qwen3.feed_forward_length": 9728,
            "qwen3.vocab_size": 1000,
        },
        {"blk.0.attn_q_norm.weight": np.ones(128, np.float32)},
    )
    cfg = config_from_gguf(GGUFFile.parse(str(tmp_path / "q3.gguf")))
    assert cfg.head_dim_ == 128
    assert cfg.qk_norm
    assert cfg.model_type == "qwen3"
