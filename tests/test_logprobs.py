"""Logprobs end-to-end: engine produces chosen-token + top-N logprobs
of the model distribution, and the OpenAI layer shapes them per spec
(chat chunk choices[].logprobs.content, legacy completions fields,
stream=false aggregation)."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY, forward, init_kv_cache
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput

PS = 8


def tiny_engine():
    cfg = EngineConfig(
        model=TINY, max_decode_slots=2, page_size=PS, num_pages=64,
        max_model_len=128, eos_token_ids=[],
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def test_engine_logprobs_match_oracle():
    engine = tiny_engine()
    engine.start()
    try:
        prompt = [5, 9, 17, 3, 11]
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 4
        b.stop_conditions.ignore_eos = True
        b.sampling_options.logprobs = 2  # top-2 + chosen

        stream = await engine.generate(b.to_dict())
        toks: list[int] = []
        lps: list[float] = []
        tops: list[dict] = []
        async for item in stream:
            toks += item.get("token_ids", [])
            lps += item.get("logprobs") or []
            tops += item.get("top_logprobs") or []
        assert len(lps) == len(toks) == 4
        assert len(tops) == 4 and all(len(t) == 2 for t in tops)

        # Oracle: greedy logprob per step from the bare forward.
        params = engine.params
        k, v = init_kv_cache(TINY, num_pages=16, page_size=PS)
        table = jnp.arange(8, dtype=jnp.int32)[None, :] + 1
        logits, k, v = forward(
            params, TINY,
            jnp.array([prompt], jnp.int32),
            jnp.arange(len(prompt), dtype=jnp.int32)[None, :], table, k, v,
        )
        cur = logits[0, -1]
        # Tolerance: the ragged engine computes first-token logits at a
        # static [slots+1, V] lm_head matmul while this oracle uses a
        # batch-1 dot — XLA:CPU lowers the two shapes through different
        # kernels, which lands within bf16 rounding (~4e-3 observed),
        # not bitwise. Greedy argmax is asserted exactly.
        for step, (tok, lp) in enumerate(zip(toks, lps)):
            full = np.asarray(jax.nn.log_softmax(cur.astype(jnp.float32)))
            assert tok == int(full.argmax())  # greedy
            assert abs(full[tok] - lp) < 2e-2
            # top dict contains the chosen (greedy) token with same lp.
            top = {int(a): float(x) for a, x in tops[step].items()}
            assert tok in top and abs(top[tok] - lp) < 2e-2
            pos = len(prompt) + step
            logits, k, v = forward(
                params, TINY,
                jnp.array([[tok]], jnp.int32),
                jnp.array([[pos]], jnp.int32), table, k, v,
            )
            cur = logits[0, 0]
    finally:
        engine.stop()


async def test_engine_no_logprobs_by_default():
    engine = tiny_engine()
    engine.start()
    try:
        b = BackendInput(token_ids=[5, 9, 17])
        b.stop_conditions.max_tokens = 2
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict())
        async for item in stream:
            assert "logprobs" not in item and "top_logprobs" not in item
    finally:
        engine.stop()


async def test_openai_chat_and_completion_logprob_shapes(tmp_path):
    """Through the preprocessor→backend→engine chain: chat chunks carry
    choices[].logprobs.content entries with token text/bytes/top_logprobs,
    completions carry the legacy fields, and aggregation merges both."""
    import sys

    sys.path.insert(0, str(__import__("os").path.dirname(__file__)))
    from fixtures import build_tiny_model_dir

    from dynamo_exp_tpu.http.service import build_pipeline_engine
    from dynamo_exp_tpu.model_card import ModelDeploymentCard
    from dynamo_exp_tpu.protocols.aggregator import (
        aggregate_chat_stream,
        aggregate_completion_stream,
    )

    model_dir = build_tiny_model_dir(str(tmp_path / "m"))
    mdc = ModelDeploymentCard.from_local_path(model_dir, "tiny")
    cfg = EngineConfig(
        model=__import__(
            "dynamo_exp_tpu.models.config", fromlist=["ModelConfig"]
        ).ModelConfig.from_pretrained(model_dir),
        max_decode_slots=2, page_size=PS, num_pages=64, max_model_len=128,
        eos_token_ids=[],
    )
    engine = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    engine.start()
    try:
        oai = build_pipeline_engine(mdc, engine)

        chat_req = {
            "model": "tiny",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 3,
            "ignore_eos": True,
            "logprobs": True,
            "top_logprobs": 2,
        }
        chunks = []
        stream = await oai.generate(chat_req)
        async for c in stream:
            chunks.append(c)
        lp_chunks = [
            c for c in chunks
            if c.choices and getattr(c.choices[0], "logprobs", None)
        ]
        assert lp_chunks, "no chat chunk carried logprobs"
        entry = lp_chunks[0].choices[0].logprobs["content"][0]
        assert {"token", "logprob", "bytes", "top_logprobs"} <= set(entry)
        assert len(entry["top_logprobs"]) == 2

        async def _replay(items):
            for c in items:
                yield c

        full = await aggregate_chat_stream(_replay(chunks))
        assert full.choices[0].logprobs["content"]

        comp_req = {
            "model": "tiny",
            "prompt": "hello world",
            "max_tokens": 3,
            "ignore_eos": True,
            "logprobs": 2,
        }
        chunks = []
        stream = await oai.generate(comp_req)
        async for c in stream:
            chunks.append(c)
        lp_chunks = [
            c for c in chunks
            if c.choices and getattr(c.choices[0], "logprobs", None)
        ]
        assert lp_chunks, "no completion chunk carried logprobs"
        lp = lp_chunks[0].choices[0].logprobs
        assert lp["tokens"] and len(lp["token_logprobs"]) == len(lp["tokens"])
        full = await aggregate_completion_stream(_replay(chunks))
        assert len(full.choices[0].logprobs["tokens"]) == 3
    finally:
        engine.stop()


async def test_top_logprobs_over_limit_rejected(tmp_path):
    """top_logprobs beyond the device's static top-N is a 400-class
    error, not silent truncation."""
    import sys

    sys.path.insert(0, str(__import__("os").path.dirname(__file__)))
    import pytest
    from fixtures import build_tiny_model_dir

    from dynamo_exp_tpu.model_card import ModelDeploymentCard
    from dynamo_exp_tpu.preprocessor.preprocessor import (
        InvalidRequestError,
        OpenAIPreprocessor,
    )
    from dynamo_exp_tpu.protocols.openai import ChatCompletionRequest

    model_dir = build_tiny_model_dir(str(tmp_path / "m"))
    mdc = ModelDeploymentCard.from_local_path(model_dir, "tiny")
    pre = OpenAIPreprocessor(mdc)
    req = ChatCompletionRequest.model_validate({
        "model": "tiny",
        "messages": [{"role": "user", "content": "hi"}],
        "logprobs": True,
        "top_logprobs": 12,
    })
    with pytest.raises(InvalidRequestError, match="top_logprobs"):
        pre.preprocess_chat(req)
