"""Shared test fixtures: build a tiny self-contained model directory
(byte-level BPE tokenizer + llama-style config + chat template) offline."""

from __future__ import annotations

import json
import os

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|{{ message.role }}|>{{ message.content }}</s>"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)

TRAIN_TEXT = [
    "hello world, this is a test of the emergency tokenizer system.",
    "the quick brown fox jumps over the lazy dog. 0123456789",
    "café naïve 日本語 emoji ☃ snowman",
    "STOP stop Stop sequences are hidden from the client output.",
    "<|user|><|assistant|><|system|></s><s>",
]


def build_tiny_model_dir(
    path: str,
    vocab_size: int = 384,
    hidden_size: int = 64,
    num_layers: int = 2,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    intermediate_size: int = 128,
    max_position_embeddings: int = 512,
) -> str:
    """Create a HF-style model dir with tokenizer + config, no weights."""
    os.makedirs(path, exist_ok=True)
    tok_json = os.path.join(path, "tokenizer.json")
    if not os.path.exists(tok_json):
        from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers

        tok = Tokenizer(models.BPE(unk_token=None))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        trainer = trainers.BpeTrainer(
            vocab_size=vocab_size,
            special_tokens=["<s>", "</s>", "<|user|>", "<|assistant|>", "<|system|>"],
            initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        )
        tok.train_from_iterator(TRAIN_TEXT, trainer)
        tok.save(tok_json)
    real_vocab = _vocab_size(tok_json)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(
            {
                "architectures": ["LlamaForCausalLM"],
                "model_type": "llama",
                "vocab_size": real_vocab,
                "hidden_size": hidden_size,
                "num_hidden_layers": num_layers,
                "num_attention_heads": num_heads,
                "num_key_value_heads": num_kv_heads,
                "intermediate_size": intermediate_size,
                "max_position_embeddings": max_position_embeddings,
                "rms_norm_eps": 1e-5,
                "rope_theta": 10000.0,
                "bos_token_id": 0,
                "eos_token_id": 1,
                "tie_word_embeddings": False,
                "torch_dtype": "bfloat16",
            },
            f,
        )
    with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
        json.dump(
            {
                "bos_token": "<s>",
                "eos_token": "</s>",
                "chat_template": CHAT_TEMPLATE,
            },
            f,
        )
    return path


def _vocab_size(tok_json: str) -> int:
    from tokenizers import Tokenizer

    return Tokenizer.from_file(tok_json).get_vocab_size()


def free_port() -> int:
    """Pick an OS-assigned free TCP port (shared by the e2e suites)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
