"""KV conservation auditor (docs/observability.md "KV conservation
auditor").

Tentpole acceptance for the fleet observability plane's third piece:

- the page ledger **conserves** across the trickiest state machines —
  KV-pressure preemption + resume, disagg extract/lease handoff
  (confirm AND reap paths), prefix sharing/COW, and spec-on decoding —
  under the `make chaos` seed sets (CHAOS_SEEDS env, like the other
  chaos suites);
- an **injected leak** (test-only double-release, orphaned-lease ref
  theft) is detected within one audit cycle and **named** — the audit
  points at the leaking sequence/lease;
- the in-loop check adds **zero host syncs** (same sync-spy shim as the
  dispatch profiler's overhead proof);
- a ledger violation dumps a flight snapshot whose `kv_audit` block
  `llmctl audit` renders.
"""

from __future__ import annotations

import asyncio
import os

import pytest

from dynamo_exp_tpu.engine.kv_manager import KvPageManager
from dynamo_exp_tpu.protocols.common import BackendInput, SamplingOptions

pytestmark = pytest.mark.chaos

CHAOS_SEEDS = [
    int(s) for s in os.environ.get("CHAOS_SEEDS", "7,21,1337").split(",")
]
PS = 4


def _engine(num_pages=8, grace=0.05, seed=0, **cfg_kw):
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models import TINY
    from dynamo_exp_tpu.parallel import single_device_mesh

    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=4,
        page_size=PS,
        num_pages=num_pages,
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
        preempt_stall_grace_s=grace,
        kv_lease_ttl_s=cfg_kw.pop("kv_lease_ttl_s", 0.2),
        **cfg_kw,
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=seed)


async def _run(eng, prompt, max_tokens=16, **sampling):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = True
    if sampling:
        b.sampling_options = SamplingOptions(**sampling)
    stream = await eng.generate(b.to_dict())
    tokens = []
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
    return tokens


def _assert_conserved(eng):
    assert eng.kv.ledger_check() == []
    audit = eng.kv_audit()
    assert audit["ok"], audit["violations"]
    assert eng.kv_ledger_violations == 0
    return audit


# ------------------------------------------------- conserved state machines
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_preemption_pressure_conserves(seed):
    """KV-pressure preemption + deterministic resume under a starved
    pool: pages release, park, re-attach — and every page stays exactly
    one of free/parked/active with refcounts balanced."""
    eng = _engine(num_pages=8)
    eng.start()
    try:
        prompts = [
            [3 + seed % 50 + i, 9, 17, 23, 4, 31, 8, 2] for i in range(3)
        ]

        async def burst():
            await asyncio.gather(*[_run(eng, p, 24) for p in prompts])

        asyncio.run(burst())
        _assert_conserved(eng)
    finally:
        eng.stop()
    assert eng.kv.ledger_check() == []


@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_prefix_sharing_and_seeded_sampling_conserve(seed):
    """Shared-prefix admissions (refcounted attaches, pending fills,
    COW on divergent writes) conserve: the shared counters and the
    per-page refcounts agree with the audit's full scan."""
    eng = _engine(num_pages=24)
    eng.start()
    try:
        shared = [11, 7, 5, 3, 2, 13, 17, 19]

        async def burst():
            await asyncio.gather(
                *[
                    _run(eng, shared + [40 + i], 12,
                         seed=seed + i, temperature=0.8)
                    for i in range(3)
                ]
            )

        asyncio.run(burst())
        _assert_conserved(eng)
    finally:
        eng.stop()


@pytest.mark.parametrize("seed", CHAOS_SEEDS[:1])
def test_spec_on_decode_conserves(seed):
    """Speculative decoding (draft provisioning + page-granular rewind)
    conserves — rewound draft pages return to the pool with refcounts
    balanced."""
    eng = _engine(num_pages=24, spec_mode="ngram")
    eng.start()
    try:
        # Repetitive prompt: the n-gram drafter actually proposes.
        prompt = [5, 6, 7, 5, 6, 7, 5, 6]
        asyncio.run(_run(eng, prompt, 20))
        _assert_conserved(eng)
    finally:
        eng.stop()


@pytest.mark.parametrize("confirm", [True, False])
def test_disagg_lease_confirm_and_reap_conserve(confirm):
    """The disagg handoff lease's two exits both conserve: delivery
    confirmation (pages park for reuse) and the failover path — the
    decode side never confirms, the reaper reclaims at TTL."""
    eng = _engine(num_pages=16, kv_lease_ttl_s=0.15)
    eng.start()
    try:
        b = BackendInput(token_ids=list(range(3, 3 + 10)))

        async def extract():
            return await eng.prefill_extract(b)

        first_token, pages, lease_id = asyncio.run(extract())
        assert pages and lease_id
        assert eng.kv.active_leases == 1
        if confirm:
            eng.confirm_kv_lease(lease_id)
        deadline = 3.0
        import time as _t

        t0 = _t.monotonic()
        while eng.kv.active_leases and _t.monotonic() - t0 < deadline:
            _t.sleep(0.02)
        assert eng.kv.active_leases == 0  # confirmed or reaped
        if not confirm:
            t0 = _t.monotonic()
            while (
                not eng.kv.lease_reclaimed_pages
                and _t.monotonic() - t0 < deadline
            ):
                _t.sleep(0.02)
            assert eng.kv.lease_reclaimed_pages > 0
        _assert_conserved(eng)
    finally:
        eng.stop()


# ------------------------------------------------------- injected leaks
@pytest.mark.ledger_leak
def test_injected_double_release_detected_within_one_cycle():
    """A test-only double-release (the classic page-accounting bug) is
    caught by the next in-loop check — within one audit cycle — and the
    engine's violation counter, the flight dump, and the audit verdict
    all fire."""
    eng = _engine(num_pages=8)
    eng.start()
    try:
        asyncio.run(_run(eng, [3, 1, 4, 1, 5, 9, 2, 6], 8))
        assert eng.kv_ledger_violations == 0
        # Inject the bug: release pages already on the free list — the
        # guarded decrement path re-appends them, the classic
        # double-release (parked pages re-park idempotently by design,
        # so the injection targets truly-free pages).
        free_pages = list(eng.kv._free)[:2]
        assert free_pages
        eng.kv.release_sequence(free_pages)
        import time as _t

        t0 = _t.monotonic()
        while eng.kv_ledger_violations == 0 and _t.monotonic() - t0 < 3.0:
            _t.sleep(0.02)
        assert eng.kv_ledger_violations > 0
        assert not eng.kv.ledger_check() == []
        audit = eng.kv_audit()
        assert not audit["ok"]
        kinds = {v["kind"] for v in audit["violations"]}
        assert "double_release" in kinds or "counter" in kinds
    finally:
        eng.stop()


@pytest.mark.ledger_leak
def test_persistent_violation_does_not_melt_the_counter():
    """A violation that persists while the engine keeps serving must
    count once per episode-kind, not once per loop iteration — the
    counter strings embed live values that legitimately drift under
    traffic, so the dedup keys on the violation *kind*."""
    eng = _engine(num_pages=16)
    eng.start()
    try:
        asyncio.run(_run(eng, [3, 1, 4, 1, 5, 9, 2, 6], 8))
        free_pages = list(eng.kv._free)[:1]
        assert free_pages
        eng.kv.release_sequence(free_pages)
        import time as _t

        t0 = _t.monotonic()
        while eng.kv_ledger_violations == 0 and _t.monotonic() - t0 < 3.0:
            _t.sleep(0.02)
        first = eng.kv_ledger_violations
        assert first > 0
        # Keep serving: counters shift every iteration, but the same
        # broken invariant kind must not re-count.
        asyncio.run(_run(eng, [9, 8, 7, 6, 5, 4, 3, 2], 8))
        _t.sleep(0.3)
        assert eng.kv_ledger_violations <= first + 1, (
            eng.kv_ledger_violations
        )
        from dynamo_exp_tpu.engine.engine import LEDGER_VIOLATIONS

        assert len(LEDGER_VIOLATIONS) < 50  # bounded, not per-iteration
    finally:
        eng.stop()


@pytest.mark.ledger_leak
def test_orphaned_lease_leak_is_named():
    """A lease whose pinned refs were stolen (simulating a lost-ref bug
    in a confirm/reap race) is *named* by the audit: the violation's
    holder list points at the lease."""
    eng = _engine(num_pages=16, kv_lease_ttl_s=60.0)
    eng.start()
    try:
        b = BackendInput(token_ids=list(range(3, 3 + 10)))
        _ft, _pages, lease_id = asyncio.run(eng.prefill_extract(b))
        assert lease_id
        lease = eng.kv._leases[lease_id]
        # Steal the lease's pins without removing the lease — the
        # orphaned-lease accounting bug this auditor exists to catch.
        eng.kv.release_sequence(lease.page_ids)
        audit = eng.kv_audit()
        assert not audit["ok"]
        named = [
            v
            for v in audit["violations"]
            if any(h == f"lease:{lease_id}" for h in v["holders"])
        ]
        assert named, audit["violations"]
        assert named[0]["kind"] == "lost_ref"
        # The process registry saw it too (in-loop check) — consume the
        # expected growth so the autouse guard's ledger_leak branch
        # verifies it.
        import time as _t

        t0 = _t.monotonic()
        while eng.kv_ledger_violations == 0 and _t.monotonic() - t0 < 3.0:
            _t.sleep(0.02)
        assert eng.kv_ledger_violations > 0
    finally:
        eng.stop()


@pytest.mark.ledger_leak
def test_violation_dumps_flight_snapshot_llmctl_audit_renders(tmp_path, capsys):
    """The violation's flight dump carries the full named audit and
    `llmctl audit` renders it (exit 1, leaker in the output)."""
    from dynamo_exp_tpu.llmctl import main as llmctl_main

    dump = str(tmp_path / "flight.jsonl")
    eng = _engine(num_pages=8, flight_dump_path=dump)
    eng.start()
    try:
        asyncio.run(_run(eng, [3, 1, 4, 1, 5, 9, 2, 6], 8))
        free_page = list(eng.kv._free)[:1]
        assert free_page
        eng.kv.release_sequence(free_page)
        import time as _t

        t0 = _t.monotonic()
        while not os.path.exists(dump) and _t.monotonic() - t0 < 3.0:
            _t.sleep(0.02)
        assert os.path.exists(dump)
    finally:
        eng.stop()
    rc = llmctl_main(["audit", dump])
    out = capsys.readouterr().out
    assert rc == 1
    assert "VIOLATION" in out
    assert "kv audit" in out

    # A healthy engine's dump renders as conserved (exit 0).
    dump2 = str(tmp_path / "flight_ok.jsonl")
    eng2 = _engine(num_pages=8, flight_dump_path=dump2)
    eng2.start()
    try:
        asyncio.run(_run(eng2, [3, 1, 4, 1, 5, 9, 2, 6], 8))
        eng2._dump_flight("test")
    finally:
        eng2.stop()
    rc2 = llmctl_main(["audit", dump2])
    out2 = capsys.readouterr().out
    assert rc2 == 0
    assert "CONSERVED" in out2


# ----------------------------------------------------- unit-level ledger
def test_ledger_check_is_pure_counter_arithmetic():
    """Unit coverage of the invariant itself, no engine: attach /
    share / lease / release / reap sequences keep ledger_check empty,
    and a forced drift breaks it."""
    kv = KvPageManager(num_pages=8, page_size=4)
    alloc = kv.allocate_sequence(list(range(10)), max_pages=8, request_id="a")
    assert alloc is not None and kv.ledger_check() == []
    # Shared attach: a second identical prompt refs the same pages.
    alloc2 = kv.allocate_sequence(list(range(10)), max_pages=8, request_id="b")
    assert alloc2 is not None and kv.ledger_check() == []
    lease = kv.grant_lease(alloc.page_ids[:2], ttl_s=60)
    assert kv.ledger_check() == []
    kv.release_sequence(alloc.page_ids)
    kv.release_sequence(alloc2.page_ids)
    assert kv.ledger_check() == []
    kv.confirm_lease(lease)
    assert kv.ledger_check() == []
    audit = kv.audit()
    assert audit["ok"], audit["violations"]
    # Forced drift: lose a reference behind the ledger's back.
    kv2 = KvPageManager(num_pages=4, page_size=4)
    a = kv2.allocate_sequence(list(range(4)), max_pages=4, request_id="x")
    kv2._records[a.page_ids[0]].ref_count = 0  # the bug
    assert kv2.audit({"seq:x": a.page_ids})["ok"] is False


def test_audit_names_the_leaking_sequence():
    kv = KvPageManager(num_pages=8, page_size=4)
    alloc = kv.allocate_sequence(list(range(8)), max_pages=8, request_id="r1")
    # Holder claims pages it no longer references (double release).
    kv.release_sequence(alloc.page_ids)
    report = kv.audit({"seq:r1": alloc.page_ids})
    assert not report["ok"]
    assert any(
        "seq:r1" in v["holders"] and v["kind"] == "lost_ref"
        for v in report["violations"]
    )


# ------------------------------------------------------- sync-spy proof
def test_ledger_check_adds_zero_host_syncs(monkeypatch):
    """Acceptance: the in-loop conservation check performs ZERO
    additional host syncs — the same workload runs with the check on
    and off under the sync-spy shim counting jax→numpy
    materializations (the dispatch profiler's overhead proof,
    tests/test_dispatch_profile.py)."""
    import numpy as np

    def run_counted(check_on: bool) -> tuple[int, int]:
        eng = _engine(num_pages=16, kv_ledger_check=check_on)
        eng.start()
        count = 0
        real = np.asarray

        def spy(a, *args, **kw):
            nonlocal count
            if type(a).__module__.startswith(("jax", "jaxlib")):
                count += 1
            return real(a, *args, **kw)

        monkeypatch.setattr(np, "asarray", spy)
        try:
            asyncio.run(_run(eng, list(range(40, 56)), 12))
        finally:
            monkeypatch.setattr(np, "asarray", real)
            eng.stop()
        return count, eng.steps

    syncs_on, steps_on = run_counted(True)
    syncs_off, steps_off = run_counted(False)
    assert steps_on == steps_off
    assert syncs_on == syncs_off, (
        f"ledger check changed host-sync count: {syncs_on} vs {syncs_off}"
    )
    assert syncs_on > 0  # the spy actually saw the consume syncs
