"""Multi-host engine bring-up: one global JAX runtime across nodes.

Reference parity: the multi-node engine bootstrap —
``/root/reference/lib/llm/src/engines.rs:41-50`` (``MultiNodeConfig``
num_nodes/node_rank/leader_addr), ``/root/reference/lib/engines/
vllm0_7/src/ray.rs:66-107`` (leader starts the cluster head, followers
join it), ``/root/reference/launch/dynamo-run/src/net.rs:1-226``
(primary-interface leader address detection).

TPU-native shape: there is no ray/MPI layer — ``jax.distributed``
forms the global runtime (one process per host, the process's local
chips join a global device list), and multi-chip execution stays
declarative: ``build_mesh`` over ``jax.devices()`` now spans hosts, and
the same ``pjit``/``shard_map`` programs run with XLA routing
collectives over ICI within a slice and DCN across slices. Leader
address discovery is either explicit (``leader_addr``) or through the
control plane: rank 0 publishes its address under a well-known KV key
and the other ranks watch for it (the reference's ray head/follower
handshake, minus ray).
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
from dataclasses import dataclass

logger = logging.getLogger(__name__)

LEADER_KEY_PREFIX = "multihost/"
LEADER_LEASE_TTL_S = 30.0
DEFAULT_DIST_PORT = 9911

# Discovery-metadata key a served instance publishes its coordinate
# under ("slice/host/chip" string — see TopologyCoordinate.parse).
TOPOLOGY_KEY = "topology"


@dataclass(frozen=True)
class TopologyCoordinate:
    """Where an instance sits in the TPU fleet: (slice, host, chip).

    The reclaim survivor selector and the topology-aware decode
    selector use :meth:`distance` as a *tiebreak prior* next to the
    TransferLedger's measured bandwidth: same-host beats same-slice
    (ICI) beats cross-slice (DCN). The coordinate is deployment
    metadata, not something JAX can introspect portably — deployments
    set ``DYN_TOPOLOGY=slice/host/chip`` per process (defaults derive
    slice 0 / host ``node_rank`` / chip 0 from :class:`MultiNodeConfig`).
    """

    slice_id: int = 0
    host: int = 0
    chip: int = 0

    # Distance tiers, widest first: 0 = same chip, 1 = same host,
    # 2 = same slice (ICI), 3 = cross-slice (DCN).
    def distance(self, other: "TopologyCoordinate") -> int:
        if self.slice_id != other.slice_id:
            return 3
        if self.host != other.host:
            return 2
        if self.chip != other.chip:
            return 1
        return 0

    def encode(self) -> str:
        return f"{self.slice_id}/{self.host}/{self.chip}"

    @classmethod
    def parse(cls, raw: str | None) -> "TopologyCoordinate | None":
        """Parse a "slice/host/chip" metadata string (missing trailing
        parts default to 0; garbage returns None — callers treat an
        unknown coordinate as maximally distant)."""
        if not raw:
            return None
        parts = str(raw).strip().split("/")
        try:
            nums = [int(p) for p in parts if p != ""]
        except ValueError:
            return None
        if not nums:
            return None
        nums = (nums + [0, 0, 0])[:3]
        return cls(slice_id=nums[0], host=nums[1], chip=nums[2])

    @classmethod
    def from_env(
        cls, cfg: "MultiNodeConfig | None" = None
    ) -> "TopologyCoordinate":
        """This process's coordinate: ``DYN_TOPOLOGY`` wins; otherwise
        derive host from the multi-node rank (single-node dev collapses
        to 0/0/0 — every peer same-host, distance a constant, so the
        ledger's measured bandwidth fully decides)."""
        parsed = cls.parse(os.environ.get("DYN_TOPOLOGY", ""))
        if parsed is not None:
            return parsed
        return cls(slice_id=0, host=cfg.node_rank if cfg else 0, chip=0)


@dataclass
class MultiNodeConfig:
    """How this process fits into the multi-host engine.

    Mirrors ``engines.rs:41-50``: ``num_nodes`` (world size),
    ``node_rank`` (this process), ``leader_addr`` ("host:port" of rank
    0's jax.distributed coordinator; None = discover via the control
    plane or, for rank 0, self-derive and publish). ``deployment``
    namespaces the published leader key so two multi-node graphs on one
    coordinator don't read each other's address.
    """

    num_nodes: int = 1
    node_rank: int = 0
    leader_addr: str | None = None
    dist_port: int = DEFAULT_DIST_PORT
    deployment: str = "default"

    @property
    def leader_key(self) -> str:
        return f"{LEADER_KEY_PREFIX}{self.deployment}/leader"

    @property
    def is_multi_node(self) -> bool:
        return self.num_nodes > 1

    @property
    def is_leader(self) -> bool:
        return self.node_rank == 0


def detect_host_ip() -> str:
    """Primary-interface address (reference: ``net.rs`` walks netlink
    for the default route's interface; the UDP-connect trick gets the
    same answer portably without sending a packet)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


async def resolve_leader_addr(
    cfg: MultiNodeConfig, discovery=None, timeout_s: float = 120.0
) -> str:
    """Rank 0 derives + publishes its coordinator address; other ranks
    read it from the control plane (etcd-equivalent KV)."""
    if cfg.leader_addr:
        return cfg.leader_addr
    key = cfg.leader_key
    if cfg.is_leader:
        addr = f"{detect_host_ip()}:{cfg.dist_port}"
        if discovery is not None:
            # Lease-scoped publish: when the leader process dies, the
            # coordinator expires the key within one TTL, so a relaunch's
            # followers can't latch onto the previous run's address.
            lease = await discovery.create_lease(ttl_s=LEADER_LEASE_TTL_S)
            await discovery.kv_put(key, addr.encode(), lease=lease)
        return addr
    if discovery is None:
        raise ValueError(
            "follower needs --dist-leader or a coordinator to discover it"
        )
    deadline = asyncio.get_running_loop().time() + timeout_s
    while asyncio.get_running_loop().time() < deadline:
        value = await discovery.kv_get(key)
        if value:
            return value.decode()
        await asyncio.sleep(0.25)
    raise TimeoutError(f"no leader address under {key!r}")


def initialize_multihost(
    cfg: MultiNodeConfig, leader_addr: str | None = None
) -> None:
    """Join the global JAX runtime. After this, ``jax.devices()`` spans
    every node and ``build_mesh`` can lay a global mesh; per-process
    data feeding uses ``jax.process_index()``."""
    import jax

    if not cfg.is_multi_node:
        return
    addr = leader_addr or cfg.leader_addr
    if not addr:
        raise ValueError("multi-node init needs the leader address")
    logger.info(
        "joining global runtime: rank %d/%d via %s",
        cfg.node_rank,
        cfg.num_nodes,
        addr,
    )
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=cfg.num_nodes,
        process_id=cfg.node_rank,
    )


async def bringup(cfg: MultiNodeConfig, discovery=None) -> None:
    """The full bring-up: resolve the leader, join the runtime."""
    if not cfg.is_multi_node:
        return
    addr = await resolve_leader_addr(cfg, discovery)
    # jax.distributed.initialize blocks until every rank dials in; run
    # it off-loop so a supervisor's event loop stays responsive.
    await asyncio.get_running_loop().run_in_executor(
        None, initialize_multihost, cfg, addr
    )
