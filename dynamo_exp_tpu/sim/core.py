"""Deterministic discrete-event loop.

A bare-bones calendar queue: callbacks scheduled at absolute sim times,
popped in (time, insertion-order) order. The insertion-order tie-break
is the determinism linchpin — simultaneous events (a burst arriving at
t=0, releases cascading at one instant) fire in exactly the order they
were scheduled, every run, so a seeded simulation's event log is
bit-identical across runs and platforms.

No wall clock anywhere: ``now`` only advances when an event fires.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable


class EventLoop:
    def __init__(self) -> None:
        self._heap: list[tuple[float, int, Callable, tuple]] = []
        self._counter = 0
        self.now = 0.0
        self.processed = 0

    def at(self, when: float, fn: Callable, *args: Any) -> None:
        """Schedule ``fn(*args)`` at absolute sim time ``when`` (clamped
        to now: the past is not available)."""
        when = max(when, self.now)
        heapq.heappush(self._heap, (when, self._counter, fn, args))
        self._counter += 1

    def after(self, delay: float, fn: Callable, *args: Any) -> None:
        self.at(self.now + max(delay, 0.0), fn, *args)

    def __len__(self) -> int:
        return len(self._heap)

    def run(
        self,
        until: float | None = None,
        max_events: int | None = None,
    ) -> None:
        """Drain the calendar. ``until`` stops the clock at a horizon
        (events beyond it stay queued); ``max_events`` is a runaway
        guard for misbehaving models, not a sampling knob."""
        while self._heap:
            if max_events is not None and self.processed >= max_events:
                raise RuntimeError(
                    f"simulation exceeded {max_events} events — "
                    "runaway model (check stall/preempt cycles)"
                )
            when, _, fn, args = self._heap[0]
            if until is not None and when > until:
                break
            heapq.heappop(self._heap)
            self.now = when
            fn(*args)
            self.processed += 1
