"""dynamo-exp-tpu: a TPU-native distributed LLM serving framework.

A ground-up, TPU-first (JAX / XLA / Pallas / pjit) framework with the
capabilities of NVIDIA Dynamo (the reference at ``rmukhopa/dynamo_exp``):

- distributed runtime (namespaces / components / endpoints, discovery with
  leases, push routing, streaming response plane)
- OpenAI-compatible HTTP frontend with SSE streaming and Prometheus metrics
- tokenization / chat-templating preprocessor and incremental detokenizing
  backend with stop-condition handling
- a native JAX/TPU inference engine: continuous batching, paged KV cache in
  HBM (Pallas ragged-paged-attention on TPU, XLA reference path on CPU),
  pjit/shard_map parallelism over a device mesh
- KV block manager with prefix reuse and host-memory offload tiers
- KV-cache-aware routing (chained-hash indexer + cost-based scheduler)
- disaggregated prefill/decode with queue-based prefill handoff

The reference is Rust/CUDA/torch; this framework is an independent,
idiomatic JAX/TPU design, not a translation.
"""

__version__ = "0.1.0"
