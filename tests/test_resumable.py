"""Resumable generation: mid-stream failover with token journaling,
deterministic continuation, and KV handoff leases.

Three layers under test (docs/fault_tolerance.md "Resumable streams"):

- **request plane** (seeded chaos harness): a decode worker killed at
  token K mid-stream — ``crash_at_token(k)`` / ``drain_timeout`` — is
  resumed on a surviving instance via the router's replay journal, and
  the spliced stream is identical to an uninterrupted run; recovery
  respects ``max_recoveries`` and end-to-end deadlines.
- **engine** (real TPUEngine on the CPU mesh): a continuation request
  (prompt + already-generated tokens re-prefilled in one batched
  dispatch) produces exactly the tokens the uninterrupted run would
  have — greedy AND seeded sampling (counter-based RNG keyed by
  (seed, absolute position)); KV handoff leases pin extracted pages and
  the engine-loop reaper reclaims them when the decode side never
  confirms delivery.
- **SSE** (full HTTP pipeline): the client-facing stream is gap-free and
  duplicate-free by sequence index across a mid-stream worker kill.

Run with ``make chaos`` (fixed seed sets) or plain pytest.
"""

import asyncio
import json
import os
import random

import pytest

from dynamo_exp_tpu.runtime import (
    Annotated,
    AsyncEngineContext,
    DeadlineExceededError,
    DistributedRuntime,
    PushRouter,
    RecoveryExhaustedError,
    ReplayJournal,
    RouterMode,
)
from dynamo_exp_tpu.runtime.transports.chaos import (
    ChaosDiscovery,
    ChaosRequestPlane,
    ChaosSchedule,
)
from dynamo_exp_tpu.runtime.transports.inproc import (
    InProcDiscovery,
    InProcRequestPlane,
)
from dynamo_exp_tpu.telemetry import get_telemetry

pytestmark = pytest.mark.chaos

SEEDS = tuple(
    int(s) for s in os.environ.get("CHAOS_SEEDS", "7,21,1337").split(",")
)

PROMPT = [11, 12, 13]
MAX_TOKENS = 10


# ------------------------------------------------------------------ helpers
def next_token(context_tokens: list[int], seed: int = 0) -> int:
    """Pure next-token function: 'greedy decoding' for a fake worker —
    depends only on the full context (and the sampling seed), exactly
    the property a re-prefilled continuation must reproduce."""
    return (sum(context_tokens) * 31 + len(context_tokens) + seed) % 97 + 3


def make_engine_worker(wid: str, calls: list, step_delay_s: float = 0.0):
    """A worker with real engine semantics over BackendInput dicts:
    token_ids are all prompt (journaled continuation tokens included),
    generation continues from the full context, one token per frame."""

    async def handler(request, context=None):
        calls.append(wid)
        toks = list(request["token_ids"])
        sc = request.get("stop_conditions") or {}
        so = request.get("sampling_options") or {}
        seed = so.get("seed") or 0
        n = sc.get("max_tokens", MAX_TOKENS)
        for _ in range(n):
            if step_delay_s:
                await asyncio.sleep(step_delay_s)
            t = next_token(toks, seed)
            toks.append(t)
            yield Annotated.from_data({"token_ids": [t]}).to_dict()
        yield Annotated.from_data(
            {
                "finish_reason": "length",
                "prompt_tokens": len(request["token_ids"]),
                "completion_tokens": n,
            }
        ).to_dict()

    return handler


def chaos_runtime(schedule: ChaosSchedule) -> DistributedRuntime:
    return DistributedRuntime(
        discovery=ChaosDiscovery(InProcDiscovery(), schedule),
        request_plane=ChaosRequestPlane(InProcRequestPlane(), schedule),
    )


async def serve_two(drt, calls, **worker_kw):
    ep = drt.namespace("resume").component("worker").endpoint("generate")
    a = await ep.serve_endpoint(make_engine_worker("a", calls, **worker_kw))
    b = await ep.serve_endpoint(make_engine_worker("b", calls, **worker_kw))
    client = await ep.client()
    await client.wait_for_instances(2, timeout=2)
    return a, b, client


def make_router(client, seed=0, **kw):
    kw.setdefault("mode", RouterMode.ROUND_ROBIN)
    kw.setdefault("backoff_base_s", 0.001)
    return PushRouter(client, rng=random.Random(seed), **kw)


def request_body(**sampling) -> dict:
    req = {
        "token_ids": list(PROMPT),
        "stop_conditions": {"max_tokens": MAX_TOKENS},
    }
    if sampling:
        req["sampling_options"] = sampling
    return req


async def collect_tokens(stream):
    tokens, final = [], None
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
        if item.get("finish_reason"):
            final = item
    return tokens, final


def expected_greedy(seed: int = 0) -> list[int]:
    toks = list(PROMPT)
    out = []
    for _ in range(MAX_TOKENS):
        t = next_token(toks, seed)
        toks.append(t)
        out.append(t)
    return out


# -------------------------------------------- mid-stream failover (tentpole)
@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("k", [1, 4, MAX_TOKENS - 1])
async def test_greedy_stream_identical_after_crash_at_token_k(seed, k):
    """Acceptance: kill the decode worker after K tokens mid-stream; the
    request completes on the survivor with a token stream identical to
    an uninterrupted run — no duplicates, no gaps, correct usage."""
    sched = ChaosSchedule(seed)
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls)
    router = make_router(client, seed)
    sched.crash_at_token(k, instance_id=a.instance_id)

    tokens, final = await collect_tokens(await router.generate(request_body()))

    assert tokens == expected_greedy()
    assert calls == ["a", "b"]  # one failover dispatch, no more
    assert final["finish_reason"] == "length"
    # Usage reflects the client's view, not the continuation's.
    assert final["prompt_tokens"] == len(PROMPT)
    assert final["completion_tokens"] == MAX_TOKENS
    # The failure registered against the dead instance.
    assert client.health.breaker(a.instance_id).consecutive_failures == 1
    await drt.close()


async def test_crash_between_last_token_and_finish_frame():
    """k == max_tokens: the budget is spent when the stream dies — the
    router closes the stream locally (synthetic length finish) instead
    of re-prefilling the whole sequence to generate nothing."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls)
    router = make_router(client)
    sched.crash_at_token(MAX_TOKENS, instance_id=a.instance_id)

    tokens, final = await collect_tokens(await router.generate(request_body()))

    assert tokens == expected_greedy()
    assert calls == ["a"]  # no re-dispatch for a spent budget
    assert final["finish_reason"] == "length"
    assert final["completion_tokens"] == MAX_TOKENS
    await drt.close()


@pytest.mark.parametrize("seed", SEEDS)
async def test_sampled_continuation_replays_deterministically(seed):
    """Two chaos runs with the same seeds produce bit-identical sampled
    streams across a mid-stream crash: the router pins the RNG seed in
    the journal, and the continuation replays it."""

    async def one_run():
        sched = ChaosSchedule(seed)
        drt = chaos_runtime(sched)
        calls: list = []
        a, b, client = await serve_two(drt, calls)
        router = make_router(client, seed)
        sched.crash_at_token(3, instance_id=a.instance_id)
        tokens, final = await collect_tokens(
            await router.generate(request_body(temperature=0.9))
        )
        injected = list(sched.injected)
        await drt.close()
        return tokens, final, calls, injected

    t1, f1, c1, i1 = await one_run()
    t2, f2, c2, i2 = await one_run()
    assert t1 == t2 and len(t1) == MAX_TOKENS
    assert f1 == f2 and c1 == c2 == ["a", "b"]
    # Same faults at the same points (instance ids are run-global
    # lease-derived counters — compare op:kind shapes).
    strip = lambda log: [":".join(x.split(":")[::2]) for x in log]
    assert strip(i1) == strip(i2)


async def test_recovery_bounded_by_max_recoveries_then_surfaces():
    """Every instance keeps dying mid-stream: after ``max_recoveries``
    failovers the break surfaces as RecoveryExhaustedError (HTTP 502)."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls)
    router = make_router(client, max_recoveries=1)
    sched.crash_at_token(2, times=2)  # initial stream AND the continuation

    stream = await router.generate(request_body())
    with pytest.raises(RecoveryExhaustedError, match="max_recoveries=1"):
        await collect_tokens(stream)
    assert calls == ["a", "b"]
    await drt.close()


async def test_no_recovery_after_deadline():
    """A stream that breaks after the request's end-to-end deadline has
    passed must NOT be resumed — the client has already given up."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls, step_delay_s=0.03)
    router = make_router(client)
    sched.crash_at_token(2, instance_id=a.instance_id)

    ctx = AsyncEngineContext()
    ctx.start_timeout(0.04)  # expires before the crash at ~0.06s
    stream = await router.generate(request_body(), ctx)
    with pytest.raises(DeadlineExceededError):
        await collect_tokens(stream)
    assert calls == ["a"]  # never re-dispatched
    await drt.close()


async def test_drain_timeout_resumes_and_labels_reason():
    """A drain whose grace period expires mid-stream is a resumable
    break, counted under reason="drain" on the recovery counter."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls)
    router = make_router(client)
    sched.drain_timeout(instance_id=a.instance_id, after_tokens=4)
    counter = get_telemetry().request_recoveries.labels("drain")
    before = counter._value.get()

    tokens, final = await collect_tokens(await router.generate(request_body()))

    assert tokens == expected_greedy()
    assert calls == ["a", "b"]
    assert counter._value.get() == before + 1
    await drt.close()


async def test_recovery_never_returns_to_previously_broken_instance():
    """Exclusion is cumulative across recoveries: with a permanently
    crashing first instance and a second that breaks once, the second
    recovery must land on the third (never-broken) instance instead of
    burning the last recovery on a known-bad one."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    ep = drt.namespace("resume").component("worker").endpoint("generate")
    a = await ep.serve_endpoint(make_engine_worker("a", calls))
    b = await ep.serve_endpoint(make_engine_worker("b", calls))
    c = await ep.serve_endpoint(make_engine_worker("c", calls))
    client = await ep.client()
    await client.wait_for_instances(3, timeout=2)
    # STATIC always picks the first healthy instance, so without the
    # cumulative-exclusion fix the second recovery would return to the
    # still-crashing `a` and exhaust the budget.
    router = make_router(client, mode=RouterMode.STATIC, max_recoveries=2)
    sched.crash_at_token(2, instance_id=a.instance_id, times=-1)
    sched.crash_at_token(4, instance_id=b.instance_id, times=1)

    tokens, final = await collect_tokens(await router.generate(request_body()))

    assert tokens == expected_greedy()
    assert calls == ["a", "b", "c"]
    assert final["finish_reason"] == "length"
    await drt.close()


async def test_explicit_target_without_selector_stays_committed():
    """generate_direct without a continuation selector keeps the old
    contract: a mid-stream break on the explicit target surfaces."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls)
    router = make_router(client)
    sched.crash_at_token(2, instance_id=a.instance_id)

    stream = await router.generate_direct(request_body(), a.instance_id)
    with pytest.raises(ConnectionError, match="crashed at token"):
        await collect_tokens(stream)
    assert calls == ["a"]
    await drt.close()


async def test_continuation_selector_enables_kv_style_failover():
    """With a continuation selector installed (the KvPushRouter wiring),
    even an explicit-target stream resumes — on the instance the
    selector picks from the survivors."""
    sched = ChaosSchedule(SEEDS[0])
    drt = chaos_runtime(sched)
    calls: list = []
    a, b, client = await serve_two(drt, calls)
    seen: list = []

    async def reselect(token_ids, exclude):
        # The continuation's token_ids include the journaled tokens —
        # the overlap estimate a KV router would price.
        seen.append((len(token_ids), set(exclude)))
        assert a.instance_id in exclude
        return b.instance_id

    router = make_router(client, continuation_selector=reselect)
    sched.crash_at_token(4, instance_id=a.instance_id)

    stream = await router.generate_direct(request_body(), a.instance_id)
    tokens, final = await collect_tokens(stream)

    assert tokens == expected_greedy()
    assert calls == ["a", "b"]
    assert seen == [(len(PROMPT) + 4, {a.instance_id})]
    await drt.close()


# ------------------------------------------------------------ journal units
def test_journal_pins_seed_and_builds_continuation():
    rng = random.Random(0)
    req = {
        "token_ids": [1, 2, 3],
        "stop_conditions": {"max_tokens": 8, "min_tokens": 4},
        "sampling_options": {"temperature": 0.7},
    }
    j = ReplayJournal.for_request(req, rng)
    seed = j.request["sampling_options"]["seed"]
    assert seed is not None  # pinned for replay
    assert req["sampling_options"].get("seed") is None  # caller untouched

    for t in (7, 8, 9):
        j.record({"token_ids": [t]})
    j.recoveries += 1
    cont = j.continuation_request()
    assert cont["token_ids"] == [1, 2, 3, 7, 8, 9]
    assert cont["resume_offset"] == 3
    assert cont["stop_conditions"]["max_tokens"] == 5
    assert cont["stop_conditions"]["min_tokens"] == 1
    assert cont["sampling_options"]["seed"] == seed


def test_journal_dedup_trims_replayed_indices():
    """A misbehaving continuation that re-emits journaled tokens is
    trimmed by sequence index — duplicate-free output, counted."""
    j = ReplayJournal.for_request({"token_ids": [1]}, random.Random(0))
    j.record({"token_ids": [10, 11]})
    j.begin_continuation()
    # Continuation (wrongly) replays index 1 before new tokens 12, 13.
    j._stream_base = 1  # stream claims to start at index 1
    before = get_telemetry().tokens_deduplicated._value.get()
    out = j.record(
        {"token_ids": [11, 12, 13], "logprobs": [-1.0, -2.0, -3.0]}
    )
    # Per-token payloads are trimmed in lockstep with token_ids.
    assert out == {"token_ids": [12, 13], "logprobs": [-2.0, -3.0]}
    assert j.tokens == [10, 11, 12, 13]
    assert get_telemetry().tokens_deduplicated._value.get() == before + 1
    # A fully duplicate frame vanishes.
    j._stream_base, j._stream_pos = 0, 0
    assert j.record({"token_ids": [10]}) is None


# ------------------------------------------- engine: continuation + leases
PS = 8


@pytest.fixture(scope="module")
def resume_engine():
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models import TINY
    from dynamo_exp_tpu.parallel import single_device_mesh

    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=4,
        page_size=PS,
        num_pages=64,
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
        kv_lease_ttl_s=0.25,  # fast reaper for the orphan tests
    )
    eng = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    eng.start()
    yield eng
    eng.stop()


async def run_engine(eng, token_ids, max_tokens, resume_offset=None, **sampling):
    from dynamo_exp_tpu.protocols.common import BackendInput, SamplingOptions

    b = BackendInput(token_ids=list(token_ids))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = True
    b.resume_offset = resume_offset
    if sampling:
        b.sampling_options = SamplingOptions(**sampling)
    stream = await eng.generate(b.to_dict())
    tokens = []
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
    return tokens


async def test_engine_greedy_continuation_token_identical(resume_engine):
    """Satellite acceptance: re-prefilling prompt + the first k generated
    tokens and continuing greedily yields exactly the uninterrupted
    run's remaining tokens — for k=1, mid-stream, and k=max_tokens-1."""
    prompt = [5, 9, 17, 23, 4, 31, 8, 2, 44, 6]
    n = 10
    full = await run_engine(resume_engine, prompt, n)
    assert len(full) == n
    for k in (1, 5, n - 1):
        cont = await run_engine(resume_engine, prompt + full[:k], n - k)
        assert full[:k] + cont == full, f"continuation diverged at k={k}"


async def test_engine_seeded_sampling_continuation_identical(resume_engine):
    """Counter-based RNG: with a pinned seed, a sampled continuation
    replays the exact draws of the uninterrupted run — the draw for the
    token at absolute position p depends only on (seed, p), never on
    window layout, batch shape, or which prefill computed the context."""
    prompt = [7, 3, 19, 28, 41, 13]
    n = 10
    so = dict(temperature=0.9, top_p=0.9, seed=12345)
    full = await run_engine(resume_engine, prompt, n, **so)
    rerun = await run_engine(resume_engine, prompt, n, **so)
    assert full == rerun  # deterministic end-to-end
    for k in (1, 4, n - 1):
        cont = await run_engine(resume_engine, prompt + full[:k], n - k, **so)
        assert full[:k] + cont == full, f"sampled continuation diverged at k={k}"


async def test_engine_penalized_continuation_restores_counts(resume_engine):
    """A continuation marked with ``resume_offset`` rebuilds the penalty
    counts from the journaled tail, so post-splice draws are penalized
    exactly like the uninterrupted run's. Greedy + presence penalty on
    the TINY model is a sharp probe: the unpenalized greedy chain
    repeats tokens, so missing counts visibly change the argmax."""
    prompt = [6, 14, 27, 35, 9]
    n, k = 10, 4
    so = dict(presence_penalty=5.0)
    full = await run_engine(resume_engine, prompt, n, **so)
    marked = await run_engine(
        resume_engine, prompt + full[:k], n - k, resume_offset=k, **so
    )
    # Counts restored → the spliced stream is token-identical to the
    # uninterrupted run (the splice token's raw-argmax draw coincides
    # here; post-splice identity is what the reconstruction guarantees).
    assert marked == full[k:]
    # Without the marker the journaled tail is plain prompt (no counts):
    # the penalty forgets those tokens and the continuation diverges —
    # proof the reconstruction actually feeds the sampler.
    unmarked = await run_engine(resume_engine, prompt + full[:k], n - k, **so)
    assert marked != unmarked


async def _wait_until(predicate, timeout_s=3.0):
    deadline = asyncio.get_running_loop().time() + timeout_s
    while not predicate():
        if asyncio.get_running_loop().time() > deadline:
            return False
        await asyncio.sleep(0.02)
    return True


async def test_engine_lease_reaper_reclaims_orphaned_extract(resume_engine):
    """Acceptance: after a simulated decode death between extract and
    inject (nobody ever confirms delivery), the prefill engine's page
    occupancy returns to its pre-request level within one lease
    period — the reaper, not a leak."""
    from dynamo_exp_tpu.protocols.common import BackendInput

    eng = resume_engine
    prompt = [3 + (i * 7) % 90 for i in range(3 * PS + 5)]
    n_pages = (len(prompt) + PS - 1) // PS
    baseline = eng.kv.active_pages
    reclaimed_before = eng.kv.lease_reclaimed_pages

    tok, pages, lease_id = await eng.prefill_extract(
        BackendInput(token_ids=prompt).to_dict()
    )
    assert lease_id and len(pages) == n_pages
    # The extract sequence has finished, yet the pages stay pinned.
    assert eng.kv.active_leases == 1
    assert eng.kv.active_pages == baseline + n_pages

    # No confirm arrives: the engine-loop reaper reclaims at TTL.
    assert await _wait_until(lambda: eng.kv.active_pages == baseline)
    assert eng.kv.active_leases == 0
    assert eng.kv.lease_reclaimed_pages == reclaimed_before + n_pages


async def test_engine_lease_confirm_releases_without_reclaim(resume_engine):
    """The happy path: a delivery ack confirms the lease — pages return
    to the pool immediately and the reaper counter does not move."""
    from dynamo_exp_tpu.protocols.common import BackendInput

    eng = resume_engine
    prompt = [4 + (i * 11) % 90 for i in range(2 * PS + 3)]
    n_pages = (len(prompt) + PS - 1) // PS
    baseline = eng.kv.active_pages
    reclaimed_before = eng.kv.lease_reclaimed_pages

    tok, pages, lease_id = await eng.prefill_extract(
        BackendInput(token_ids=prompt).to_dict()
    )
    assert eng.kv.active_pages == baseline + n_pages
    eng.confirm_kv_lease(lease_id)
    assert await _wait_until(lambda: eng.kv.active_pages == baseline)
    assert eng.kv.active_leases == 0
    assert eng.kv.lease_reclaimed_pages == reclaimed_before  # no reap


async def test_prefill_worker_leaves_lease_to_reaper_on_delivery_failure(
    resume_engine,
):
    """Worker-level: KV delivery to a dead decode worker fails → the
    lease is NOT confirmed (the reaper owns cleanup), and the pull loop
    survives."""
    from dynamo_exp_tpu.disagg import PrefillWorker, RemotePrefillRequest
    from dynamo_exp_tpu.disagg.protocol import kv_signature
    from dynamo_exp_tpu.runtime.transports.inproc import InProcWorkQueue

    eng = resume_engine
    baseline = eng.kv.active_pages
    worker = PrefillWorker(eng, InProcWorkQueue())
    req = RemotePrefillRequest(
        request_id="dead-decode-1",
        token_ids=[5 + (i * 13) % 90 for i in range(PS + 3)],
        return_addr="127.0.0.1:1",  # nothing listens: delivery fails
        page_size=PS,
        model=kv_signature(eng.cfg),
    )
    await worker._serve_one(req.to_bytes())
    assert worker.failed == 1 and worker.served == 0
    # Lease left behind for the reaper, which then restores occupancy.
    assert await _wait_until(lambda: eng.kv.active_pages == baseline)
    assert eng.kv.active_leases == 0


# ------------------------------------------------- SSE layer (full pipeline)
async def test_sse_stream_gapless_and_duplicate_free_across_failover(
    tiny_model_dir,
):
    """Acceptance: HTTP → preprocessor → backend → push router over the
    chaos plane; the decode worker dies mid-stream; the client's SSE
    stream is identical to an uninterrupted run with strictly increasing
    sequence indices and exact usage."""
    from aiohttp.test_utils import TestClient, TestServer

    from dynamo_exp_tpu.http import HttpService, build_pipeline_engine
    from dynamo_exp_tpu.model_card import ModelDeploymentCard

    mdc = ModelDeploymentCard.from_local_path(tiny_model_dir, display_name="tiny")

    async def run_sse(crash_at: int | None):
        sched = ChaosSchedule(SEEDS[0])
        drt = chaos_runtime(sched)
        calls: list = []
        a, b, client = await serve_two(drt, calls)
        if crash_at is not None:
            sched.crash_at_token(crash_at, instance_id=a.instance_id)
        router = make_router(client)
        svc = HttpService()
        svc.manager.add_completion_model(
            "tiny", build_pipeline_engine(mdc, router)
        )
        http = TestClient(TestServer(svc.app))
        await http.start_server()
        r = await http.post(
            "/v1/completions",
            json={
                "model": "tiny",
                "prompt": list(PROMPT),
                "max_tokens": MAX_TOKENS,
                "stream": True,
                "stream_options": {"include_usage": True},
            },
        )
        assert r.status == 200
        raw = (await r.read()).decode()
        await http.close()
        await drt.close()
        chunks = [
            json.loads(line[6:])
            for line in raw.split("\n")
            if line.startswith("data: ") and line != "data: [DONE]"
        ]
        text = "".join(
            c["choices"][0].get("text") or "" for c in chunks if c.get("choices")
        )
        seq = [c["seq_index"] for c in chunks if c.get("seq_index") is not None]
        usage = next((c["usage"] for c in chunks if c.get("usage")), None)
        assert raw.strip().endswith("data: [DONE]")  # stream closed cleanly
        return text, seq, usage, calls

    clean_text, clean_seq, clean_usage, clean_calls = await run_sse(None)
    text, seq, usage, calls = await run_sse(4)

    assert calls == ["a", "b"] and clean_calls == ["a"]
    # Unbroken: the spliced stream is byte-identical to the clean run.
    assert text == clean_text and len(text) > 0
    # Gap-free, duplicate-free by sequence index; all tokens accounted.
    assert seq == sorted(set(seq)) and seq == clean_seq
    assert seq[-1] == MAX_TOKENS
    assert usage == clean_usage
    assert usage["prompt_tokens"] == len(PROMPT)
    assert usage["completion_tokens"] == MAX_TOKENS


async def test_sse_layer_drops_duplicate_seq_index_chunks():
    """Defense in depth: chunks arriving at the HTTP layer with an
    already-emitted sequence index are dropped before the wire."""
    from aiohttp.test_utils import TestClient, TestServer

    from dynamo_exp_tpu.http import HttpService
    from dynamo_exp_tpu.runtime import ResponseStream

    def chunk(text, si):
        return {
            "id": "c",
            "object": "text_completion",
            "created": 1,
            "model": "tiny",
            "choices": [{"index": 0, "text": text}],
            "seq_index": si,
        }

    class ReplayingEngine:
        async def generate(self, request, context=None):
            ctx = context or AsyncEngineContext()

            async def _gen():
                yield chunk("a", 1)
                yield chunk("b", 2)
                yield chunk("b", 2)  # duplicate splice artifact
                yield chunk("a", 1)  # stale replay
                yield chunk("c", 3)

            return ResponseStream(_gen(), ctx)

    svc = HttpService()
    svc.manager.add_completion_model("tiny", ReplayingEngine())
    http = TestClient(TestServer(svc.app))
    await http.start_server()
    r = await http.post(
        "/v1/completions",
        json={"model": "tiny", "prompt": "x", "stream": True},
    )
    raw = (await r.read()).decode()
    await http.close()
    texts = [
        json.loads(line[6:])["choices"][0]["text"]
        for line in raw.split("\n")
        if line.startswith("data: ") and line != "data: [DONE]"
        if json.loads(line[6:]).get("choices")
    ]
    assert texts == ["a", "b", "c"]
