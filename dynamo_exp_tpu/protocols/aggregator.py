"""Stream -> full-response aggregation for ``stream=false`` requests.

The service always streams internally; unary responses are folded from
the chunk stream. Capability parity with
``/root/reference/lib/llm/src/protocols/openai/*/aggregator.rs``.
"""

from __future__ import annotations

from typing import AsyncIterator

from .openai import (
    ChatChoice,
    ChatCompletionChunk,
    ChatCompletionResponse,
    ChatMessage,
    CompletionChoice,
    CompletionChunk,
    CompletionResponse,
    Usage,
)


async def aggregate_chat_stream(
    chunks: AsyncIterator[ChatCompletionChunk],
) -> ChatCompletionResponse:
    pieces: dict[int, list[str]] = {}
    finish: dict[int, str | None] = {}
    roles: dict[int, str] = {}
    lp_content: dict[int, list] = {}
    usage: Usage | None = None
    meta: ChatCompletionChunk | None = None
    async for chunk in chunks:
        meta = meta or chunk
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            idx = choice.index
            if choice.delta.role:
                roles[idx] = choice.delta.role
            if choice.delta.content:
                pieces.setdefault(idx, []).append(choice.delta.content)
            if choice.logprobs and choice.logprobs.get("content"):
                lp_content.setdefault(idx, []).extend(
                    choice.logprobs["content"]
                )
            if choice.finish_reason is not None:
                finish[idx] = choice.finish_reason
    if meta is None:
        raise ValueError("empty response stream")
    indices = sorted(set(pieces) | set(finish) | set(roles)) or [0]
    choices = [
        ChatChoice(
            index=i,
            message=ChatMessage(
                role=roles.get(i, "assistant"), content="".join(pieces.get(i, []))
            ),
            finish_reason=finish.get(i),
            logprobs=(
                {"content": lp_content[i]} if i in lp_content else None
            ),
        )
        for i in indices
    ]
    return ChatCompletionResponse(
        id=meta.id,
        created=meta.created,
        model=meta.model,
        choices=choices,
        usage=usage,
    )


async def aggregate_completion_stream(
    chunks: AsyncIterator[CompletionChunk],
) -> CompletionResponse:
    pieces: dict[int, list[str]] = {}
    finish: dict[int, str | None] = {}
    lp_merge: dict[int, dict] = {}
    usage: Usage | None = None
    meta: CompletionChunk | None = None
    async for chunk in chunks:
        meta = meta or chunk
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            if choice.text:
                pieces.setdefault(choice.index, []).append(choice.text)
            if choice.logprobs:
                agg = lp_merge.setdefault(
                    choice.index,
                    {"tokens": [], "token_logprobs": [], "top_logprobs": []},
                )
                agg["tokens"] += choice.logprobs.get("tokens") or []
                agg["token_logprobs"] += (
                    choice.logprobs.get("token_logprobs") or []
                )
                agg["top_logprobs"] += choice.logprobs.get("top_logprobs") or []
            if choice.finish_reason is not None:
                finish[choice.index] = choice.finish_reason
    if meta is None:
        raise ValueError("empty response stream")
    indices = sorted(set(pieces) | set(finish)) or [0]
    for agg in lp_merge.values():
        if not agg["top_logprobs"]:  # logprobs=0: null, not [] (OpenAI)
            agg["top_logprobs"] = None
    choices = [
        CompletionChoice(
            index=i,
            text="".join(pieces.get(i, [])),
            finish_reason=finish.get(i),
            logprobs=lp_merge.get(i),
        )
        for i in indices
    ]
    return CompletionResponse(
        id=meta.id,
        created=meta.created,
        model=meta.model,
        choices=choices,
        usage=usage,
    )
