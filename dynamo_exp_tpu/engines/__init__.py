"""Engine registry: test engines and the TPU engine behind one seam.

Capability parity with ``/root/reference/lib/llm/src/engines.rs``: "core"
engines speak token-in/token-out (``BackendInput`` -> ``LLMEngineOutput``)
and get wrapped by the preprocessor + backend; "full" engines accept
OpenAI requests directly. ``MultiNodeConfig`` carries multi-host bring-up
parameters (JAX distributed coordinator instead of Ray/torch.distributed).
"""

from __future__ import annotations

from dataclasses import dataclass

from .echo import EchoEngineCore, EchoEngineFull


@dataclass
class MultiNodeConfig:
    """Multi-host engine bring-up (maps to jax.distributed.initialize)."""

    num_nodes: int = 1
    node_rank: int = 0
    coordinator_address: str = ""


def make_engine(name: str, **kwargs):
    """Engine factory by name (reference: ``engines.rs:82`` make_engine_*).

    ``jax``/``tpu`` is the native TPU engine: pass either ``cfg=`` (a
    built ``EngineConfig``) or ``preset=`` (a model preset name, e.g.
    ``"llama-1b"``) plus any ``EngineConfig`` field overrides. The echo
    engines validate the serving pipeline without hardware.
    """
    if name == "echo_core":
        return EchoEngineCore(**kwargs)
    if name == "echo_full":
        return EchoEngineFull(**kwargs)
    if name in ("jax", "tpu"):
        from ..engine import EngineConfig, TPUEngine

        cfg = kwargs.pop("cfg", None)
        if cfg is None:
            from ..models import PRESETS

            preset = kwargs.pop("preset", "llama-1b")
            model = kwargs.pop("model", None) or PRESETS[preset]
            ctor = {
                k: kwargs.pop(k)
                for k in list(kwargs)
                if k in EngineConfig.__dataclass_fields__
            }
            cfg = EngineConfig(model=model, **ctor)
        return TPUEngine(cfg, **kwargs)
    raise ValueError(f"unknown engine {name!r}")


__all__ = ["EchoEngineCore", "EchoEngineFull", "MultiNodeConfig", "make_engine"]
