"""Paged attention over a page-table-indexed KV cache (XLA reference path).

Design (TPU-first, replaces what vLLM's PagedAttention CUDA kernels gave the
reference for free — see SURVEY.md §2.9):

- The KV cache is a global page pool per layer: ``[num_pages, page_size,
  num_kv_heads, head_dim]``. Sequences own pages via a per-slot page table.
- Write-then-gather: a step first scatters its new K/V into the pool at
  (page_table[pos // ps], pos % ps), then attention gathers the sequence's
  pages and masks by position. Prefill (B=1, T=bucket) and decode
  (B=slots, T=1) share one code path, so prefix-cache hits need no special
  attention kernel — cached pages are simply already written.
- Static shapes throughout: page tables are fixed width, masks handle the
  ragged reality, so XLA compiles once per (B, T, Pmax) bucket.

This module is the always-correct XLA path and the CPU-mesh test oracle.
The gather is bounded by the caller (``forward(attn_pages=...)`` slices
the page table to the live context), and the QK/PV matmuls run in the
cache dtype (bfloat16) with float32 accumulation on the MXU. The fast
path for prefill AND decode is the ragged Pallas kernel in
``ops/ragged_attention.py``, which this path cross-checks in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def write_kv_pages(
    k_cache: jnp.ndarray,  # [P, ps, Hkv*D] (heads collapsed into lanes)
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,  # [N, Hkv*D] flattened new tokens
    v_new: jnp.ndarray,
    page_ids: jnp.ndarray,  # [N] int32 global page id per new token
    offsets: jnp.ndarray,  # [N] int32 in-page offset per new token
    valid: jnp.ndarray,  # [N] bool — False rows are dropped
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V rows into the page pool. Invalid rows are given an
    out-of-range page id, which XLA's ``mode="drop"`` scatter discards —
    no write happens for them at all.

    The pool keeps (kv head, head_dim) collapsed into one trailing
    dimension: TPU tiling pads the last dim to 128 lanes, so a separate
    D=64 axis would double every pool's HBM footprint (and every
    gather's traffic); Hkv*D is 128-aligned for the shapes we serve.
    """
    num_pages = k_cache.shape[0]
    # Out-of-range page id for invalid rows => XLA drops the scatter row.
    safe_pages = jnp.where(valid, page_ids, num_pages)
    k_cache = k_cache.at[safe_pages, offsets].set(
        k_new.astype(k_cache.dtype), mode="drop"
    )
    v_cache = v_cache.at[safe_pages, offsets].set(
        v_new.astype(v_cache.dtype), mode="drop"
    )
    return k_cache, v_cache


def paged_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k_cache: jnp.ndarray,  # [P, ps, Hkv*D]
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, Pmax] int32
    q_positions: jnp.ndarray,  # [B, T] int32 global position of each query
    sm_scale: float | None = None,
    window: int | jnp.ndarray | None = None,
    softcap: float | None = None,
) -> jnp.ndarray:
    """Causal attention of queries against their sequences' pages.

    Returns [B, T, H, D]. Positions beyond a query's own position are
    masked, so garbage in not-yet-written slots never leaks. ``window``
    (mistral/gemma2 sliding-window attention) additionally masks keys
    older than ``q_pos - window + 1`` — it may be a traced scalar, so a
    scan over layers can alternate window widths (gemma2). ``softcap``
    applies gemma2's tanh cap to the scores before masking.
    """
    B, T, H, D = q.shape
    P, ps, _ = k_cache.shape
    Hkv = k_cache.shape[2] // D
    S = page_table.shape[1] * ps
    scale = sm_scale if sm_scale is not None else D ** -0.5

    # Gather this batch's pages: [B, Pmax, ps, Hkv*D] -> [B, S, Hkv, D]
    k = k_cache[page_table].reshape(B, S, Hkv, D)
    v = v_cache[page_table].reshape(B, S, Hkv, D)

    # QK/PV matmuls run on the MXU in the cache dtype (bfloat16 in
    # production) with float32 accumulation; softmax stays float32.
    qpk = H // Hkv
    qg = q.reshape(B, T, Hkv, qpk, D).astype(k.dtype)
    scores = (
        jnp.einsum(
            "bthqd,bshd->bhqts", qg, k, preferred_element_type=jnp.float32
        )
        * scale
    )  # [B,Hkv,qpk,T,S] f32

    if softcap is not None:
        scores = jnp.tanh(scores / softcap) * softcap
    kv_pos = jnp.arange(S, dtype=jnp.int32)[None, None, None, None, :]
    qp = q_positions[:, None, None, :, None]
    mask = kv_pos <= qp  # causal by position
    if window is not None:
        mask &= kv_pos > qp - window
    scores = jnp.where(mask, scores, -1e30)

    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqts,bshd->bthqd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, T, H, D).astype(q.dtype)


def dense_causal_attention(
    q: jnp.ndarray,  # [B, T, H, D]
    k: jnp.ndarray,  # [B, T, Hkv, D]
    v: jnp.ndarray,
    sm_scale: float | None = None,
) -> jnp.ndarray:
    """Plain causal attention (no cache) — used by tests as the oracle and
    by the ring-attention building block."""
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    qpk = H // Hkv
    scale = sm_scale if sm_scale is not None else D ** -0.5
    qg = q.reshape(B, T, Hkv, qpk, D).astype(k.dtype)
    scores = (
        jnp.einsum(
            "bthqd,bshd->bhqts", qg, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    i = jnp.arange(T)[:, None]
    j = jnp.arange(T)[None, :]
    scores = jnp.where(j <= i, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqts,bshd->bthqd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, T, H, D).astype(q.dtype)
