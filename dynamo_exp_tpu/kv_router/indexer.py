"""Global prefix index: which workers hold which KV blocks.

Capability parity with ``/root/reference/lib/llm/src/kv_router/indexer.rs``
(``RadixTree::{find_matches,apply_event,remove_worker}`` :239-391,
``KvIndexer`` :499-608, ``KvIndexerSharded`` :677-790), redesigned around
the chained-hash property of our blocks: because each block's sequence
hash commits to its entire prefix (``tokens.py``), prefix containment is
a chain walk — a flat ``hash -> workers`` map plus contiguity bookkeeping
is equivalent to the reference's radix tree with O(1) updates.

Single-writer: events are applied on the indexer's asyncio task, queries
run on the same loop — the same discipline the reference enforces with
its event channel.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from collections import defaultdict
from typing import Sequence

from ..tokens import HASH_ALGO_VERSION, compute_block_hashes_for_seq
from .protocols import KvCacheEventData, OverlapScores, RouterEvent

logger = logging.getLogger(__name__)


class RadixIndex:
    """hash -> set(worker) with per-worker reverse index."""

    def __init__(self):
        self._workers_by_hash: dict[int, set[int]] = defaultdict(set)
        self._hashes_by_worker: dict[int, set[int]] = defaultdict(set)

    def apply_event(self, event: RouterEvent) -> None:
        if event.hash_version != HASH_ALGO_VERSION:
            # Warned once at decode (protocols.from_dict). A mismatched
            # peer's hashes live in a disjoint seed space and can never
            # match a local query — indexing them would only grow
            # unmatchable state for the life of that worker.
            return
        w = event.worker_id
        data: KvCacheEventData = event.data
        if data.kind == "stored":
            for h in data.block_hashes:
                self._workers_by_hash[h].add(w)
                self._hashes_by_worker[w].add(h)
        elif data.kind == "removed":
            for h in data.block_hashes:
                self._workers_by_hash.get(h, set()).discard(w)
                self._hashes_by_worker.get(w, set()).discard(h)
                if not self._workers_by_hash.get(h):
                    self._workers_by_hash.pop(h, None)
        else:
            logger.warning("unknown kv event kind %r", data.kind)

    def remove_worker(self, worker_id: int) -> None:
        for h in self._hashes_by_worker.pop(worker_id, set()):
            s = self._workers_by_hash.get(h)
            if s is not None:
                s.discard(worker_id)
                if not s:
                    self._workers_by_hash.pop(h, None)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        """Longest contiguous matched prefix per worker: worker w scores
        i+1 only if it held blocks 0..i."""
        scores: dict[int, int] = {}
        for i, h in enumerate(seq_hashes):
            workers = self._workers_by_hash.get(h)
            if not workers:
                break
            for w in workers:
                if scores.get(w, 0) == i:
                    scores[w] = i + 1
            if not any(v == i + 1 for v in scores.values()):
                break  # no worker extends past i; deeper blocks can't match
        return OverlapScores({w: s for w, s in scores.items() if s > 0})

    @property
    def num_blocks(self) -> int:
        return len(self._workers_by_hash)


class KvIndexer:
    """Event-pump wrapper: subscribes to a subject on the event plane and
    keeps the index current; offers block hashing + match queries."""

    def __init__(self, block_size: int):
        self.block_size = block_size
        self.index = RadixIndex()
        self._task: asyncio.Task | None = None
        self.events_applied = 0

    def block_hashes(self, token_ids: Sequence[int]) -> list[int]:
        return compute_block_hashes_for_seq(token_ids, self.block_size)

    def find_matches_for_request(self, token_ids: Sequence[int]) -> OverlapScores:
        return self.index.find_matches(self.block_hashes(token_ids))

    def apply(self, event: RouterEvent) -> None:
        self.index.apply_event(event)
        self.events_applied += 1

    def remove_worker(self, worker_id: int) -> None:
        self.index.remove_worker(worker_id)

    async def start(self, event_plane, subject: str) -> None:
        if self._task is not None:
            return

        # Subscribe (fully registered on return) before the task runs so no
        # event can slip between start() returning and the pump's first
        # iteration.
        subscription = await event_plane.subscribe(subject)

        async def pump():
            async for payload in subscription:
                try:
                    self.apply(RouterEvent.from_dict(payload))
                except Exception:
                    logger.exception("bad kv event: %r", payload)

        self._task = asyncio.create_task(pump(), name=f"kv-indexer[{subject}]")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None


class KvIndexerSharded:
    """Shards the index by hash for very large clusters (reference:
    ``KvIndexerSharded``, indexer.rs:677-790). Queries fan out and merge."""

    def __init__(self, block_size: int, num_shards: int = 4):
        self.block_size = block_size
        self.shards = [RadixIndex() for _ in range(num_shards)]

    def _shard(self, worker_id: int) -> RadixIndex:
        return self.shards[worker_id % len(self.shards)]

    def apply(self, event: RouterEvent) -> None:
        self._shard(event.worker_id).apply_event(event)

    def remove_worker(self, worker_id: int) -> None:
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches_for_request(self, token_ids: Sequence[int]) -> OverlapScores:
        hashes = compute_block_hashes_for_seq(token_ids, self.block_size)
        merged: dict[int, int] = {}
        for shard in self.shards:
            merged.update(shard.find_matches(hashes).scores)
        return OverlapScores(merged)
