"""llmctl: control CLI over the live model-registration plane.

Capability parity with ``/root/reference/launch/llmctl/src/main.rs``
(:101-454): add / list / remove model registrations against the running
control plane, so operators can attach models to ingress (or detach
them) without touching workers.

    python -m dynamo_exp_tpu.llmctl --coordinator HOST:PORT \
        http add chat-model foo/v1 dynamo.TpuWorker.generate \
        [--model-path /models/foo]
    python -m dynamo_exp_tpu.llmctl --coordinator HOST:PORT http list
    python -m dynamo_exp_tpu.llmctl --coordinator HOST:PORT \
        http remove model foo/v1

Entries added here are NOT lease-scoped (no worker owns them): they
represent operator intent and persist until removed, exactly like the
reference's etcd writes from llmctl.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import re
import sys

from .local_model import MDC_BUCKET, MODELS_PREFIX, ModelEntry

_TYPES = {"chat-model": "chat", "completion-model": "completion", "model": "both"}


def _slug(name: str) -> str:
    return name.replace("/", "--")


async def add_model(drt, args) -> int:
    entry = ModelEntry(
        name=args.model_name,
        endpoint=_qualify(args.endpoint_name, args.namespace),
        model_type=_TYPES[args.model_type],
        mdc_key=_slug(args.model_name),
    )
    if args.model_path:
        from .model_card import ModelDeploymentCard

        mdc = ModelDeploymentCard.from_local_path(
            args.model_path, args.model_name
        )
        await drt.object_store.put(
            MDC_BUCKET, entry.mdc_key, mdc.to_json().encode()
        )
    # Key carries the model type so chat + completion registrations of
    # one name coexist (and remove stays type-scoped).
    key = (
        f"{MODELS_PREFIX}{_slug(args.model_name)}/llmctl-{entry.model_type}"
    )
    await drt.discovery.kv_put(key, entry.to_bytes())
    print(f"added {entry.model_type} model {entry.name} -> {entry.endpoint}")
    return 0


async def list_models(drt, args) -> int:
    entries = await drt.discovery.kv_get_prefix(MODELS_PREFIX)
    want = _TYPES.get(args.model_type or "model", "both")
    rows = []
    for key, raw in sorted(entries.items()):
        try:
            e = ModelEntry.from_bytes(raw)
        except (ValueError, TypeError, KeyError):
            continue
        if want != "both" and e.model_type not in (want, "both"):
            continue
        rows.append((e.name, e.model_type, e.endpoint, key.rsplit("/", 1)[-1]))
    if args.json:
        print(json.dumps([
            {"name": n, "type": t, "endpoint": ep, "owner": o}
            for n, t, ep, o in rows
        ]))
        return 0
    if not rows:
        print("no models registered")
        return 0
    width = max(len(r[0]) for r in rows)
    for name, mtype, ep, owner in rows:
        print(f"{name:<{width}}  {mtype:<10}  {ep}  ({owner})")
    return 0


async def remove_model(drt, args) -> int:
    """Remove registrations of the given type only — a model registered
    as both chat and completion under one name keeps the other entry
    (type-scoped like the reference llmctl,
    ``/root/reference/launch/llmctl/src/main.rs:101-454``)."""
    want = _TYPES.get(args.model_type or "model", "both")
    prefix = f"{MODELS_PREFIX}{_slug(args.model_name)}/"
    entries = await drt.discovery.kv_get_prefix(prefix)
    removed = 0
    for key, raw in entries.items():
        try:
            e = ModelEntry.from_bytes(raw)
        except (ValueError, TypeError, KeyError):
            # Undecodable entries are unreachable by type-scoped remove;
            # the untyped 'model' remove is the escape hatch that clears
            # them (otherwise garbage keys would be undeletable forever).
            if want == "both":
                await drt.discovery.kv_delete(key)
                removed += 1
            continue
        if want != "both" and e.model_type not in (want, "both"):
            continue
        await drt.discovery.kv_delete(key)
        removed += 1
    if not removed:
        print(f"no {args.model_type} registration for {args.model_name}",
              file=sys.stderr)
        return 1
    print(f"removed {removed} registration(s) for {args.model_name}")
    return 0


def _qualify(endpoint: str, namespace: str) -> str:
    """component.endpoint or namespace.component.endpoint → dyn:// URL."""
    if endpoint.startswith("dyn://"):
        endpoint = endpoint[len("dyn://") :]
    parts = endpoint.split(".")
    if len(parts) == 2:
        parts = [namespace, *parts]
    if len(parts) != 3:
        raise SystemExit(
            f"endpoint must be [ns.]component.endpoint, got {endpoint!r}"
        )
    return "dyn://" + ".".join(parts)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl", description=__doc__)
    # Required for the control-plane planes; ``trace`` works offline
    # from recorder files (validated in run()).
    p.add_argument("--coordinator", default="", help="control plane host:port")
    p.add_argument("-n", "--namespace", default="dynamo")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http", help="HTTP-served model registrations")
    hsub = http.add_subparsers(dest="command", required=True)

    add = hsub.add_parser("add")
    add.add_argument("model_type", choices=sorted(_TYPES))
    add.add_argument("model_name")
    add.add_argument("endpoint_name")
    add.add_argument("--model-path", default="", help="publish an MDC too")

    lst = hsub.add_parser("list")
    lst.add_argument("model_type", nargs="?", choices=sorted(_TYPES))
    lst.add_argument("--json", action="store_true")

    rm = hsub.add_parser("remove")
    rm.add_argument("model_type", choices=sorted(_TYPES))
    rm.add_argument("model_name")

    # Live disagg-router reconfiguration (reference: DisaggRouterConf in
    # etcd with a watch, disagg_router.rs:24-262). ``set`` takes effect
    # on running decode workers within one watch push — no restarts.
    disagg = sub.add_parser(
        "disagg", help="conditional disagg-router config (live-watched)"
    )
    dsub = disagg.add_subparsers(dest="command", required=True)
    dget = dsub.add_parser("get")
    dget.add_argument("model_name")
    dset = dsub.add_parser("set")
    dset.add_argument("model_name")
    dset.add_argument("--max-local-prefill-length", type=int, required=True)
    dset.add_argument("--max-prefill-queue-size", type=int, default=2)

    # Graceful drain: publish drain intent for an instance. The serving
    # process watches the drain prefix, republishes itself with
    # ``draining`` metadata (routers stop sending new work on their next
    # discovery snapshot), and finishes in-flight requests.
    drain = sub.add_parser(
        "drain", help="gracefully drain a worker instance (stop new work)"
    )
    drain.add_argument("instance_id", type=int)

    # Spot-reclamation notice: like drain, but with a hard deadline the
    # worker's ReclaimController triages under — live KV migration for
    # what fits, journal failover for the rest
    # (docs/fault_tolerance.md "Spot reclamation & live migration").
    reclaim = sub.add_parser(
        "reclaim",
        help="send a reclaim notice (deadline-bounded drain + live KV "
        "migration) to a worker instance",
    )
    reclaim.add_argument("instance_id", type=int)
    reclaim.add_argument(
        "--grace-s",
        type=float,
        default=30.0,
        help="grace window in seconds before the instance is killed "
        "(default 30)",
    )

    # Offline trace reconstruction from the telemetry recorder JSONL
    # (``DYN_TRACE_FILE``): no argument lists recorded traces; with a
    # trace_id (full/prefix) or request id, pretty-prints its span tree.
    trace = sub.add_parser(
        "trace", help="reconstruct a request's span timeline from recorder JSONL"
    )
    trace.add_argument(
        "trace_id", nargs="?", default="",
        help="trace id (full or prefix) or request id; omit to list traces",
    )
    trace.add_argument(
        "--trace-file", action="append", default=None,
        help="recorder JSONL path(s); defaults to $DYN_TRACE_FILE "
             "(rotated generations are read automatically)",
    )
    trace.add_argument(
        "--why", action="store_true",
        help="decompose the trace into latency components (request "
             "anatomy waterfall) instead of the raw span timeline",
    )

    # Worst-N request listing (docs/observability.md "Request
    # anatomy"): offline over a recorder span file, or live from every
    # instance's bounded exemplar ring via the coordinator.
    slow = sub.add_parser(
        "slow", help="list the slowest requests with their dominant "
                     "latency component",
    )
    slow.add_argument(
        "--trace-file", action="append", default=None,
        help="recorder JSONL path(s) for offline mode; defaults to "
             "$DYN_TRACE_FILE; omit (and pass --coordinator) to scrape "
             "the live fleet's exemplar rings",
    )
    slow.add_argument("-n", "--count", type=int, default=10)
    slow.add_argument(
        "--by", choices=("edge", "ttft", "itl"), default="edge",
        help="sort key (default: edge latency)",
    )
    slow.add_argument(
        "--why", action="store_true",
        help="print the full anatomy waterfall per request, not just "
             "the one-line summary",
    )

    # Workload fingerprint (docs/observability.md "Workload
    # fingerprint"): characterize a recorded workload — span file, sim
    # trace, or bench capture — as a deterministic hashable digest,
    # optionally diffing against a pinned reference or replaying it
    # into a sim workload trace.
    fprint = sub.add_parser(
        "fingerprint", help="characterize a workload from spans / trace "
                            "/ bench files (offline)",
    )
    fprint.add_argument(
        "path", help="span JSONL, sim workload trace, bench capture, or "
                     "a saved fingerprint JSON",
    )
    fprint.add_argument(
        "--kind", choices=("auto", "spans", "trace", "bench", "ref"),
        default="auto",
        help="input format (default: sniff from content)",
    )
    fprint.add_argument("--json", action="store_true",
                        help="print the full fingerprint as JSON")
    fprint.add_argument(
        "--out", default="",
        help="also write the fingerprint JSON here (pin it via "
             "DYN_WORKLOAD_REF for the live drift watch)",
    )
    fprint.add_argument(
        "--ref", default="",
        help="reference fingerprint JSON to score drift against",
    )
    fprint.add_argument(
        "--replay-out", default="",
        help="write a sim workload trace drawn from the fingerprint "
             "(the fingerprint->sim bridge; replay with "
             "`llmctl sim users --trace-in FILE`)",
    )
    fprint.add_argument("--seed", type=int, default=0,
                        help="seed for --replay-out draws")
    fprint.add_argument(
        "--requests", type=int, default=None,
        help="request count for --replay-out (default: the "
             "fingerprint's own n)",
    )

    # Offline flight-dump rendering (docs/observability.md "Engine
    # flight recorder & watchdog"): a dump file holds one block per
    # dump (watchdog stall / SIGUSR1 / crash); render a block as a
    # per-slot timeline the way `trace` renders spans.
    flight = sub.add_parser(
        "flight", help="render an engine flight-recorder dump (offline)"
    )
    flight.add_argument("dump_file", help="flight dump JSONL path")
    flight.add_argument(
        "--index", type=int, default=-1,
        help="which dump block to render (default: the last)",
    )
    flight.add_argument(
        "--list", action="store_true",
        help="list the file's dump blocks instead of rendering one",
    )
    flight.add_argument(
        "--why", action="store_true",
        help="reconstruct per-request latency anatomy from the dump's "
             "admit/first_token/preempt/stall/finish events",
    )
    flight.add_argument(
        "--req", default="",
        help="with --why: only the given request id",
    )

    # Live fleet dashboard (docs/observability.md "Fleet plane"):
    # scrape every discovered instance's stats plane into one rolled-up
    # view — per-instance occupancy, queue depth, shed/preempt rates,
    # per-link transfer MB/s — tolerant of dead/draining members.
    top = sub.add_parser(
        "top", help="live fleet dashboard (per-instance + per-link rollup)"
    )
    top.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh interval in seconds",
    )
    top.add_argument(
        "--once", action="store_true",
        help="print one snapshot and exit (no refresh loop)",
    )
    top.add_argument(
        "--json", action="store_true",
        help="print one machine-readable snapshot (rollup + per-"
             "instance views) and exit; implies --once",
    )

    # Offline KV conservation audit rendering (docs/observability.md
    # "KV conservation auditor"): flight dumps carry the full named
    # audit (every page classified, refcounts cross-checked against
    # live sequences/leases); render the verdict and name the leaker.
    audit = sub.add_parser(
        "audit", help="render the KV conservation audit from a flight dump"
    )
    audit.add_argument("dump_file", help="flight dump JSONL path")
    audit.add_argument(
        "--index", type=int, default=-1,
        help="which dump block to audit (default: the last)",
    )

    # Offline bench regression comparator (docs/observability.md "Fleet
    # plane"): compare two bench captures (raw bench.py JSONL or the
    # checked-in BENCH_r*.json wrappers) and flag >threshold tok/s or
    # TTFT/ITL regressions per metric, platform-tag aware. The
    # pre-merge CI step runs it over the checked-in trajectory.
    bench = sub.add_parser(
        "bench", help="bench trajectory tools (offline)"
    )
    bsub = bench.add_subparsers(dest="command", required=True)
    bcmp = bsub.add_parser("compare")
    bcmp.add_argument("old_file", help="baseline bench capture")
    bcmp.add_argument("new_file", help="candidate bench capture")
    bcmp.add_argument(
        "--threshold", type=float, default=0.10,
        help="regression threshold as a fraction (default 0.10 = 10%%)",
    )

    # Offline static analysis (docs/static_analysis.md): run the
    # dynlint AST invariant checkers (host-sync / determinism /
    # thread-ownership / recompile-hazard) over the package tree.
    # `--rule` and `--baseline` support incremental adoption during
    # large refactors; `make lint` and the tier-1 gate run the full
    # zero-unwaived-findings check.
    lint = sub.add_parser(
        "lint", help="dynlint: AST invariant checks (offline)"
    )
    from .analysis.runner import add_lint_args

    add_lint_args(lint)

    # Offline cluster simulation (docs/simulation.md): replay a seeded
    # workload through the real admission/routing/preemption/planner
    # policy code against modeled instances and print the SimReport.
    sim = sub.add_parser(
        "sim", help="discrete-event cluster simulation (offline, seeded)"
    )
    sim.add_argument(
        "workload",
        choices=("burst", "ramp", "users", "diurnal"),
        help="burst: the overload_burst chaos scenario; ramp: linear "
        "arrival-rate ramp; users: open-loop synthetic user stream; "
        "diurnal: periodic burst swinging between --rps-start and "
        "--rps-end (the coldstart/provisioning study, docs/aot.md)",
    )
    sim.add_argument("--seed", type=int, default=0)
    sim.add_argument("--requests", type=int, default=None,
                     help="request count (burst n / users cap)")
    sim.add_argument("--duration-s", type=float, default=300.0)
    sim.add_argument("--rps-start", type=float, default=1.0)
    sim.add_argument("--rps-end", type=float, default=12.0)
    sim.add_argument("--trace-out", default="",
                     help="also save the workload as a JSONL trace file")
    sim.add_argument("--trace-in", default="",
                     help="replay a JSONL trace file instead of generating "
                     "(overrides the workload kind)")
    sim.add_argument("--instances", type=int, default=1)
    sim.add_argument("--slots", type=int, default=8)
    sim.add_argument("--pages", type=int, default=256)
    sim.add_argument("--page-size", type=int, default=16)
    sim.add_argument("--max-inflight", type=int, default=64)
    sim.add_argument("--shed-watermark", type=int, default=None)
    sim.add_argument(
        "--planner", choices=("none", "reactive", "slo"), default="none"
    )
    sim.add_argument("--max-tpu-budget", type=int, default=8)
    sim.add_argument("--ttft-slo-s", type=float, default=2.0)
    sim.add_argument("--itl-slo-s", type=float, default=0.2)
    sim.add_argument(
        "--fit-spans", action="append", default=[],
        help="telemetry recorder JSONL to fit service times from",
    )
    sim.add_argument(
        "--fit-bench", action="append", default=[],
        help="bench.py JSON (or BENCH_r*.json wrapper) to fit from",
    )
    sim.add_argument("--events", action="store_true",
                     help="print the event log instead of the report")
    sim.add_argument(
        "--prefix-groups", type=int, default=0,
        help="assign arrivals to this many shared-prefix groups "
        "(docs/prefix_sharing.md; 0 = no shared prefixes)",
    )
    sim.add_argument(
        "--prefix-len", type=int, default=0,
        help="shared prefix length in tokens (default: half the "
        "prompt, capped at the prompt)",
    )
    sim.add_argument(
        "--no-prefix-sharing", action="store_true",
        help="private-copy baseline: prefix groups route by overlap "
        "but every request pays full pages",
    )
    sim.add_argument(
        "--host-pages", type=int, default=0,
        help="modeled G2 host-tier pages per instance (docs/"
        "engine_perf.md 'Predictive KV tiering'; enables proactive "
        "offload under KV pressure; 0 = reactive baseline)",
    )
    sim.add_argument(
        "--g3-pages", type=int, default=0,
        help="modeled durable G3 store pages per instance (docs/"
        "fault_tolerance.md 'Durable KV & corruption containment'; "
        "evicted cold blocks demote there and survive --restart-at-s; "
        "0 = G2-only baseline)",
    )
    sim.add_argument(
        "--restart-at-s", type=float, default=None,
        help="restart drill: hard-restart the busiest instance at this "
        "sim time (journal failover for in-flight work; respawns on "
        "the same modeled G3 disk after the provision delay)",
    )
    sim.add_argument(
        "--no-kv-packing", action="store_true",
        help="first-fit admission baseline (disable footprint-packed "
        "admission)",
    )
    sim.add_argument(
        "--period-s", type=float, default=300.0,
        help="diurnal workload: burst period in seconds (rate swings "
        "between --rps-start and --rps-end each period)",
    )
    sim.add_argument(
        "--provision-s", type=float, default=None,
        help="override the worker add -> serving delay (both the "
        "modeled spawn time and the SLO planner's provision_s hint) — "
        "the coldstart-study knob, docs/aot.md",
    )

    # Offline AOT precompilation (docs/aot.md): enumerate the compile
    # lattice, AOT-compile it into the persistent compilation cache,
    # and warm-boot engines from it.
    aot = sub.add_parser(
        "aot", help="AOT compile lattice: enumerate, precompile, warm-boot"
    )
    aot.add_argument(
        "command", choices=("compile", "list", "warm", "smoke"),
        help="compile: AOT-compile every manifest entry into the cache "
        "dir; list: print the manifest (no compilation); warm: boot an "
        "engine via prewarm and report; smoke: boot twice against a "
        "tmp cache dir and fail on any second-boot compile miss",
    )
    aot.add_argument("--preset", default="tiny",
                     help="built-in model preset (random weights)")
    aot.add_argument("--compile-cache-dir", default="",
                     help="persistent compilation cache directory "
                     "(default: $DYN_COMPILE_CACHE; smoke uses a tmp dir)")
    aot.add_argument("--tp", type=int, default=1)
    aot.add_argument("--max-decode-slots", type=int, default=4)
    aot.add_argument("--page-size", type=int, default=16)
    aot.add_argument("--num-pages", type=int, default=0, help="0 = auto")
    aot.add_argument("--max-model-len", type=int, default=512)
    aot.add_argument("--decode-window", type=int, default=8)
    aot.add_argument("--prefill-chunk", type=int, default=128)
    aot.add_argument("--kv-dtype", default="bfloat16",
                     choices=["bfloat16", "float32"])
    aot.add_argument("--spec", default="off",
                     help="speculative drafter (adds the draft-carrying "
                     "variants to the lattice)")
    aot.add_argument("--no-lp", action="store_true",
                     help="drop the logprob variants (halves the lattice "
                     "for deployments that never serve logprobs)")

    # Sim-in-the-loop autotuner (docs/tuning.md): seeded coordinate
    # descent over the declarative knob space, scored in the cluster
    # simulator against a workload target, optionally live-validated on
    # the tiny harness, emitted as a bootable config artifact.
    tune = sub.add_parser(
        "tune", help="autotune engine/planner knobs against a workload "
                     "target (offline, seeded)"
    )
    tgt = tune.add_mutually_exclusive_group(required=True)
    tgt.add_argument(
        "--fingerprint", default="",
        help="target workload fingerprint JSON "
             "(`llmctl fingerprint --out`)",
    )
    tgt.add_argument(
        "--trace", default="",
        help="target a sim workload trace JSONL (tuned via its "
             "fingerprint)",
    )
    tgt.add_argument(
        "--workload", default="",
        choices=("burst", "ramp", "diurnal", "users"),
        help="target a named synthetic workload",
    )
    tune.add_argument("--seed", type=int, default=0)
    tune.add_argument("--budget", type=int, default=64,
                      help="max sim evaluations (rung-0 + rung-1)")
    tune.add_argument("--eval-seeds", type=int, default=2,
                      help="seeds per full evaluation")
    tune.add_argument("--requests", type=int, default=None,
                      help="requests per evaluation (default: the "
                           "fingerprint's own n)")
    tune.add_argument("--rate-rps", type=float, default=None,
                      help="override the target's arrival rate")
    tune.add_argument("--instances", type=int, default=1,
                      help="modeled fleet size the knobs are tuned for")
    tune.add_argument(
        "--planner", action="store_true",
        help="run the SLO planner in every evaluation and include the "
             "planner/SLO knobs in the search space",
    )
    tune.add_argument("--journal", default="",
                      help="JSONL trial journal path (audit + resume)")
    tune.add_argument(
        "--resume", action="store_true",
        help="replay an existing --journal as an evaluation cache "
             "(byte-identical continuation of an interrupted run)",
    )
    tune.add_argument(
        "--top-k", type=int, default=0,
        help="validate this many top candidates on the live tiny "
             "harness (sim-vs-live rank agreement) before recommending "
             "(0 = skip; boots real engines)",
    )
    tune.add_argument("--out", default="",
                      help="write the tuned-config artifact JSON here")
    tune.add_argument("--preset", default="tiny",
                      help="model preset the artifact's engine block "
                           "and AOT manifest are built for")
    tune.add_argument("--max-model-len", type=int, default=512)
    tune.add_argument("--kv-dtype", default="bfloat16",
                      choices=["bfloat16", "float32"])
    tune.add_argument("--tp", type=int, default=1)
    tune.add_argument(
        "--no-manifest", action="store_true",
        help="skip embedding the AOT CompileManifest in the artifact",
    )
    tune.add_argument(
        "--check", action="store_true",
        help="exit nonzero unless the recommendation beats the default "
             "config in-sim (the `make tune-smoke` gate)",
    )
    tune.add_argument("--json", action="store_true",
                      help="print the result summary as JSON")
    return p


def run_trace(args) -> int:
    import os

    from .telemetry import find_trace, list_traces, load_spans, render_timeline

    paths = args.trace_file or (
        [os.environ["DYN_TRACE_FILE"]] if os.environ.get("DYN_TRACE_FILE") else []
    )
    if not paths:
        print(
            "no trace files: pass --trace-file or set DYN_TRACE_FILE",
            file=sys.stderr,
        )
        return 2
    spans = load_spans(paths)
    if not spans:
        print("no spans recorded", file=sys.stderr)
        return 1
    if not args.trace_id:
        for tid, n, dur, stage in list_traces(spans):
            print(f"{tid}  {n:3d} spans  {dur * 1e3:9.1f}ms  {stage}")
        return 0
    group = find_trace(spans, args.trace_id)
    if not group:
        print(f"no trace matching {args.trace_id!r}", file=sys.stderr)
        return 1
    if getattr(args, "why", False):
        from .telemetry import anatomy_from_spans, render_anatomy

        anatomy = anatomy_from_spans(group)
        if anatomy is None:
            print("trace has no decomposable spans", file=sys.stderr)
            return 1
        print(render_anatomy(anatomy))
        return 0
    print(render_timeline(group))
    return 0


def _resolve_trace_paths(args) -> list[str]:
    import os

    return args.trace_file or (
        [os.environ["DYN_TRACE_FILE"]]
        if os.environ.get("DYN_TRACE_FILE")
        else []
    )


def run_slow_offline(args) -> int:
    """`llmctl slow` over a recorder span file: decompose every trace
    and list the worst offenders by the chosen latency axis."""
    from .telemetry import (
        anatomy_from_spans,
        load_spans,
        render_anatomy,
        render_slow,
    )

    paths = _resolve_trace_paths(args)
    if not paths:
        print(
            "no trace files: pass --trace-file / set DYN_TRACE_FILE, or "
            "pass --coordinator to scrape the live fleet",
            file=sys.stderr,
        )
        return 2
    spans = load_spans(paths)
    by_trace: dict[str, list] = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    anatomies = [
        a
        for tid in sorted(by_trace)
        if (a := anatomy_from_spans(by_trace[tid])) is not None
    ]
    if not anatomies:
        print("no spans recorded", file=sys.stderr)
        return 1
    print(render_slow(anatomies, n=args.count, by=args.by))
    if args.why:
        keys = {"edge": lambda a: a.edge_latency_s,
                "ttft": lambda a: a.ttft_s or 0.0,
                "itl": lambda a: a.itl_s or 0.0}
        worst = sorted(anatomies, key=lambda a: -keys[args.by](a))
        for a in worst[: args.count]:
            print()
            print(render_anatomy(a))
    return 0


async def run_slow_live(drt, args) -> int:
    """`llmctl slow` against a live fleet: collect every instance's
    bounded worst-N exemplar ring (``metrics()["anatomy_slow"]``)."""
    import asyncio

    from .telemetry import RequestAnatomy, render_anatomy, render_slow

    try:
        instances = await drt.discovery.list_instances("")
    except Exception as e:  # noqa: BLE001 - no discovery = nothing to list
        print(f"discovery unavailable: {e}", file=sys.stderr)
        return 1

    async def one(info) -> object:
        try:
            return await asyncio.wait_for(
                drt.request_plane.scrape_stats(info), 5.0
            )
        except Exception as e:  # noqa: BLE001 - dead member, skipped
            return e

    results = await asyncio.gather(*[one(i) for i in instances])
    anatomies: list[RequestAnatomy] = []
    for info, m in zip(instances, results):
        if not isinstance(m, dict):
            continue
        for entry in m.get("anatomy_slow") or []:
            if isinstance(entry, dict):
                a = RequestAnatomy.from_dict(entry)
                if not a.instances:
                    a.instances = (str(info.instance_id),)
                anatomies.append(a)
    if not anatomies:
        print("no request anatomy exemplars in the fleet yet")
        return 0
    print(render_slow(anatomies, n=args.count, by=args.by))
    if args.why:
        for a in sorted(anatomies, key=lambda x: -x.edge_latency_s)[: args.count]:
            print()
            print(render_anatomy(a))
    return 0


def run_fingerprint(args) -> int:
    """`llmctl fingerprint`: characterize a recorded workload. Sniffs
    the input format unless --kind pins it, prints the digest +
    distribution summary, and optionally pins/diffs/replays it."""
    from .telemetry import (
        drift_score,
        fingerprint_from_bench,
        fingerprint_from_spans,
        fingerprint_from_trace,
        load_fingerprint,
        load_spans,
        render_fingerprint,
    )

    kind = args.kind
    if kind == "auto":
        kind = _sniff_fingerprint_kind(args.path)
        if kind is None:
            print(
                f"cannot tell what {args.path!r} is — pass --kind",
                file=sys.stderr,
            )
            return 2
    try:
        if kind == "spans":
            fp = fingerprint_from_spans(load_spans([args.path]))
        elif kind == "trace":
            fp = fingerprint_from_trace(args.path)
        elif kind == "bench":
            fp = fingerprint_from_bench(args.path)
        else:
            fp = load_fingerprint(args.path)
    except OSError as e:
        print(f"cannot read {args.path!r}: {e}", file=sys.stderr)
        return 2
    if fp.n == 0:
        print(f"no requests found in {args.path!r} (kind={kind})",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(fp.to_dict(), indent=2))
    else:
        print(render_fingerprint(fp))
    if args.ref:
        ref = load_fingerprint(args.ref)
        print(f"drift vs {args.ref}: {drift_score(fp, ref):.4f}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(fp.to_dict(), f, indent=2)
        print(f"# fingerprint -> {args.out}", file=sys.stderr)
    if args.replay_out:
        from .sim.workload import save_trace
        from .telemetry import replay_workload

        reqs = replay_workload(fp, seed=args.seed, n=args.requests)
        n = save_trace(args.replay_out, reqs)
        print(f"# {n} replayed requests -> {args.replay_out}",
              file=sys.stderr)
    return 0


def _sniff_fingerprint_kind(path: str) -> str | None:
    """Guess a fingerprint input's format from its first record."""
    head = ""
    try:
        with open(path) as f:
            head = f.read(65536).strip()
    except OSError:
        pass
    if not head:
        # A shared DYN_TRACE_FILE records to per-process/rotated
        # siblings (path.pidN, path.N) that load_spans expands —
        # sniff the first sibling so the operator can point at the
        # configured path verbatim.
        import glob as _glob

        sib_re = re.compile(r"^(\.pid\d+)?(\.\d+)*$")
        for cand in sorted(_glob.glob(path + ".*")):
            if sib_re.fullmatch(cand[len(path):]):
                try:
                    with open(cand) as f:
                        head = f.read(65536).strip()
                except OSError:
                    continue
                if head:
                    break
    if not head:
        return None
    first = head.splitlines()[0].strip()
    try:
        obj = json.loads(first)
    except ValueError:
        # Multi-line JSON document (a saved fingerprint or a bench
        # wrapper written with indent).
        try:
            obj = json.loads(head)
        except ValueError:
            return None
    if not isinstance(obj, dict):
        return None
    if "isl_hist" in obj:
        return "ref"
    # Recorder lines wrap the span event: {"ts": ..., "event": {...}}.
    ev = obj.get("event")
    if isinstance(ev, dict) and ev.get("type") == "span":
        return "spans"
    if "stage" in obj and "trace_id" in obj:
        return "spans"
    if "arrival_s" in obj and "prompt_len" in obj:
        return "trace"
    if "metric" in obj or "tail" in obj or "parsed" in obj:
        return "bench"
    return None


def run_flight(args) -> int:
    import os

    from .telemetry import load_dumps, render_flight

    if not os.path.exists(args.dump_file):
        print(f"no such dump file: {args.dump_file}", file=sys.stderr)
        return 2
    blocks = load_dumps(args.dump_file)
    if not blocks:
        print("no flight dumps in file", file=sys.stderr)
        return 1
    if args.list:
        for i, b in enumerate(blocks):
            h = b["header"]
            print(
                f"{i}  reason={h.get('reason', '?')}  "
                f"{len(b['events'])} events  pid={h.get('pid', '?')}"
            )
        return 0
    try:
        block = blocks[args.index]
    except IndexError:
        print(
            f"dump index {args.index} out of range ({len(blocks)} blocks)",
            file=sys.stderr,
        )
        return 1
    if getattr(args, "why", False):
        from .telemetry import anatomy_from_flight, render_anatomy

        anatomies = anatomy_from_flight(block, args.req or None)
        if not anatomies:
            print(
                "no complete request (admit..finish) in this dump block",
                file=sys.stderr,
            )
            return 1
        for i, a in enumerate(anatomies):
            if i:
                print()
            print(render_anatomy(a))
        return 0
    print(render_flight(block))
    return 0


async def run_aot(args) -> int:
    """The offline AOT plane (docs/aot.md): enumerate / precompile /
    warm-boot the engine compile lattice. ``list`` is weight-free; the
    other commands build a random-weight engine of the given shape."""
    import os
    import tempfile

    from .aot import (
        aot_compile,
        build_manifest,
        cache_dir_from_env,
        enable_persistent_cache,
        manifest_for_engine,
    )
    from .engine import EngineConfig, TPUEngine, resolve_attn_impl
    from .models import PRESETS
    from .parallel.mesh import build_mesh

    mcfg = PRESETS[args.preset]
    max_len = min(args.max_model_len, mcfg.max_position_embeddings)
    cfg = EngineConfig(
        model=mcfg,
        max_decode_slots=args.max_decode_slots,
        page_size=args.page_size,
        num_pages=args.num_pages
        or (args.max_decode_slots * (max_len // args.page_size + 1) + 64),
        max_model_len=max_len,
        tp=args.tp,
        eos_token_ids=[],
        kv_dtype=args.kv_dtype,
        decode_window=args.decode_window,
        prefill_chunk=args.prefill_chunk,
        spec_mode=args.spec,
    )
    cache_dir = args.compile_cache_dir or cache_dir_from_env()
    include_lp = not args.no_lp

    if args.command == "list":
        import jax

        mesh = build_mesh(tp=cfg.tp, sp=cfg.sp)
        impl, interpret = resolve_attn_impl(cfg, mesh)
        manifest = build_manifest(
            cfg, attn_impl=impl, mesh_shape=dict(mesh.shape),
            jax_version=jax.__version__, interpret=interpret,
            include_lp=include_lp,
        )
        print(manifest.to_json(indent=2))
        print(
            f"# {len(manifest.ragged)} ragged variants, "
            f"{len(manifest.move_buckets)} move buckets, "
            f"hash {manifest.hash()}",
            file=sys.stderr,
        )
        return 0

    if cache_dir:
        enable_persistent_cache(cache_dir)

    async def traffic(engine, n: int = 2, prompt_len: int = 24) -> None:
        """A tiny mixed probe burst (greedy + seeded rows)."""

        async def one(i: int):
            req = {
                "token_ids": list(range(3 + i, 3 + i + prompt_len)),
                "stop_conditions": {"max_tokens": 8, "ignore_eos": True},
            }
            if i % 2:
                req["sampling_options"] = {"seed": i, "temperature": 0.8}
            stream = await engine.generate(req)
            async for _ in stream:
                pass

        await asyncio.gather(*[one(i) for i in range(n)])

    if args.command == "compile":
        engine = TPUEngine(cfg, seed=0)
        manifest = manifest_for_engine(engine, include_lp=include_lp)
        report = aot_compile(engine, manifest, cache_dir=cache_dir)
        print(json.dumps(report.to_dict(), indent=2))
        return 1 if report.failed else 0

    if args.command == "warm":
        engine = TPUEngine(cfg, seed=0)
        manifest = manifest_for_engine(engine, include_lp=include_lp)
        report = engine.prewarm(manifest, cache_dir=cache_dir)
        await traffic(engine)
        m = engine.metrics()
        print(
            json.dumps(
                {
                    "manifest_hash": report.manifest_hash,
                    "prewarmed_variants": report.variants,
                    "prewarm_seconds": round(report.seconds, 3),
                    "compiled_ragged_variants": m["compiled_ragged_variants"],
                    "ragged_compile_misses_after_warm": m["dispatch"][
                        "ragged"
                    ]["compile_misses"],
                },
                indent=2,
            )
        )
        engine.stop()
        return 0

    # smoke: two warm boots against one cache dir; the second must
    # compile nothing — no ragged misses, no variant growth, no new
    # cache entries (the pre-merge `make prewarm-smoke` gate). Always a
    # FRESH tmp dir (the help text's promise): running against a shared
    # $DYN_COMPILE_CACHE would skip the population half of the test and
    # write probe entries into a production cache.
    cache_dir = tempfile.mkdtemp(prefix="dynamo_aot_smoke_")
    enable_persistent_cache(cache_dir)

    async def boot() -> tuple[dict, int]:
        engine = TPUEngine(cfg, seed=0)
        engine.prewarm(
            manifest_for_engine(engine, include_lp=include_lp),
            cache_dir=cache_dir,
        )
        await traffic(engine)
        m = engine.metrics()
        engine.stop()
        return m, len(os.listdir(cache_dir))

    m1, files1 = await boot()
    m2, files2 = await boot()
    misses = m2["dispatch"]["ragged"]["compile_misses"]
    new_files = files2 - files1
    verdict = {
        "cache_dir": cache_dir,
        "boot1_prewarm_s": m1["prewarm_seconds"],
        "boot2_prewarm_s": m2["prewarm_seconds"],
        "boot2_ragged_compile_misses": misses,
        "boot2_new_cache_files": new_files,
        "boot2_variant_growth_after_traffic": m2[
            "compiled_ragged_variants"
        ]
        - m1["compiled_ragged_variants"],
        "ok": misses == 0
        and new_files == 0
        and m2["compiled_ragged_variants"] == m1["compiled_ragged_variants"],
    }
    print(json.dumps(verdict, indent=2))
    if not verdict["ok"]:
        print("prewarm-smoke FAILED: second boot compiled", file=sys.stderr)
        return 1
    return 0


async def run_tune(args) -> int:
    """The autotuner plane (docs/tuning.md): search in the simulator,
    optionally validate top-K on the live tiny harness, emit the
    bootable config artifact."""
    from .tune import artifact as tune_artifact
    from .tune import search as tune_search
    from .tune import validate as tune_validate

    fp = None
    if args.fingerprint:
        from .telemetry.fingerprint import load_fingerprint

        fp = load_fingerprint(args.fingerprint)
        target = tune_search.target_from_fingerprint(
            fp, requests=args.requests, rate_rps=args.rate_rps
        )
    elif args.trace:
        target = tune_search.target_from_trace(
            args.trace, requests=args.requests, rate_rps=args.rate_rps
        )
        fp = target.fingerprint
    else:
        target = tune_search.TuneTarget(
            kind="synthetic",
            name=args.workload,
            requests=args.requests or 64,
            rate_rps=args.rate_rps,
        )

    settings = tune_search.SearchSettings(
        seed=args.seed,
        budget=args.budget,
        eval_seeds=args.eval_seeds,
        planner=args.planner,
        base_sim={"initial_instances": args.instances},
    )
    result = tune_search.run_search(
        target,
        settings,
        journal_path=args.journal or None,
        resume=args.resume,
    )
    summary = {
        "target": result.target_digest,
        "seed": result.seed,
        "trials": result.trials,
        "best_overrides": result.best_overrides,
        "best_score": result.best_score,
        "default_score": result.default_score,
        "improvement": result.improvement,
    }

    validation = None
    if args.top_k > 0:
        candidates = tune_search.top_candidates(result, args.top_k)
        report = await tune_validate.validate_candidates(
            candidates, target, seed=args.seed
        )
        validation = {
            "kendall_tau": report["kendall_tau"],
            "top1_agreement": report["top1_agreement"],
            "agreed": report["agreed"],
            "sim_scores": report["sim_scores"],
            "live_scores": report["live_scores"],
        }
        summary["validation"] = validation

    if args.out:
        manifest = None
        if not args.no_manifest:
            import jax

            from .aot import build_manifest
            from .engine import EngineConfig, resolve_attn_impl
            from .models import PRESETS
            from .parallel.mesh import build_mesh

            mcfg = PRESETS[args.preset]
            max_len = min(args.max_model_len, mcfg.max_position_embeddings)
            shape = {
                "max_model_len": max_len,
                "kv_dtype": args.kv_dtype,
                "tp": args.tp,
            }
            cfg = EngineConfig(
                model=mcfg,
                eos_token_ids=[],
                **shape,
                **tune_artifact.resolved_live_knobs(result.best_overrides),
            )
            mesh = build_mesh(tp=cfg.tp, sp=cfg.sp)
            impl, interpret = resolve_attn_impl(cfg, mesh)
            manifest = build_manifest(
                cfg, attn_impl=impl, mesh_shape=dict(mesh.shape),
                jax_version=jax.__version__, interpret=interpret,
            )
        else:
            shape = {
                "max_model_len": args.max_model_len,
                "kv_dtype": args.kv_dtype,
                "tp": args.tp,
            }
        art = tune_artifact.build_artifact(
            result,
            preset=args.preset,
            shape=shape,
            manifest=manifest,
            fingerprint=fp,
            validation=validation,
        )
        tune_artifact.write_artifact(art, args.out)
        summary["artifact"] = args.out
        summary["config_hash"] = art["config_hash"]

    if args.json:
        print(json.dumps(summary, indent=2, sort_keys=True))
    else:
        print(
            f"target {summary['target']}  trials {summary['trials']}  "
            f"score {summary['best_score']} vs default "
            f"{summary['default_score']} "
            f"({summary['improvement']:+.1%})"
        )
        for k, v in sorted(result.best_overrides.items()):
            print(f"  {k} = {v}")
        if validation is not None:
            print(
                f"validation: kendall_tau {validation['kendall_tau']}, "
                f"top-1 {'agrees' if validation['top1_agreement'] else 'DISAGREES'}"
            )
        if args.out:
            print(f"artifact -> {args.out}")

    if validation is not None and not validation["agreed"]:
        print(
            "tune: sim-vs-live validation DISAGREES; recommendation "
            "not trustworthy",
            file=sys.stderr,
        )
        return 1
    if args.check and result.best_score <= result.default_score:
        print(
            "tune --check: recommendation does not beat the default "
            "config in-sim",
            file=sys.stderr,
        )
        return 1
    return 0


def run_audit(args) -> int:
    """Render the KV conservation audit carried by a flight dump's
    snapshot: the per-state page counts, the verdict, and — on a
    violation — the leaking page with the holder(s) that still claim
    it (``seq:<request_id>`` / ``lease:<id>``)."""
    import os

    from .telemetry import load_dumps

    if not os.path.exists(args.dump_file):
        print(f"no such dump file: {args.dump_file}", file=sys.stderr)
        return 2
    blocks = load_dumps(args.dump_file)
    if not blocks:
        print("no flight dumps in file", file=sys.stderr)
        return 1
    try:
        block = blocks[args.index]
    except IndexError:
        print(
            f"dump index {args.index} out of range ({len(blocks)} blocks)",
            file=sys.stderr,
        )
        return 1
    header = block.get("header", {})
    audit = (block.get("snapshot") or {}).get("kv_audit")
    if not isinstance(audit, dict):
        print(
            "dump carries no kv_audit snapshot (engine predates the "
            "conservation auditor, or the snapshot failed)",
            file=sys.stderr,
        )
        return 1
    counts = audit.get("counts", {})
    print(
        f"kv audit — reason={header.get('reason', '?')} "
        f"pool={audit.get('pool', '?')} leases={audit.get('leases', 0)}"
    )
    print(
        "  "
        + "  ".join(f"{k}={counts.get(k, 0)}" for k in sorted(counts))
        + f"  held={audit.get('held_pages', '?')}"
        f"  ref_total={audit.get('ref_total', '?')}"
    )
    # G3 persistent tier (docs/fault_tolerance.md "Durable KV &
    # corruption containment"): present only when the engine ran with
    # a store configured — its own O(1) conservation arithmetic rides
    # in the same snapshot.
    g3 = audit.get("g3")
    g3_violations: list[str] = []
    if isinstance(g3, dict):
        print(
            f"  g3 store: resident={g3.get('resident', 0)} "
            f"adopted={g3.get('adopted', 0)} stores={g3.get('stores', 0)} "
            f"evictions={g3.get('evictions', 0)} "
            f"quarantined={g3.get('quarantined', 0)} "
            f"checksum_failures={g3.get('checksum_failures', 0)} "
            f"degraded={g3.get('degraded', False)}"
        )
        g3_violations = list(g3.get("violations") or [])
    violations = audit.get("violations", [])
    if not violations and not g3_violations:
        print("  CONSERVED: every page accounted for, refcounts balance")
        return 0
    if violations:
        print(f"  {len(violations)} VIOLATION(S):")
        for v in violations:
            page = v.get("page")
            where = f"page {page}" if page is not None else "counters"
            holders = ", ".join(v.get("holders") or []) or "no live holder"
            print(
                f"    {where}: {v.get('kind')} — {v.get('detail')} [{holders}]"
            )
    if g3_violations:
        print(f"  {len(g3_violations)} G3 VIOLATION(S):")
        for s in g3_violations:
            print(f"    {s}")
    return 1


def run_bench_compare(args) -> int:
    import os

    from .telemetry.bench_compare import (
        compare_bench,
        load_bench_lines,
        render_compare,
    )

    for path in (args.old_file, args.new_file):
        if not os.path.exists(path):
            print(f"no such bench file: {path}", file=sys.stderr)
            return 2
    report = compare_bench(
        load_bench_lines(args.old_file),
        load_bench_lines(args.new_file),
        threshold=args.threshold,
    )
    print(render_compare(report, args.old_file, args.new_file))
    return 0 if report.ok else 1


async def run_top(drt, args) -> int:
    """Live fleet dashboard: scrape + render on an interval (`--once`
    prints a single snapshot for scripts and tests; `--json` prints the
    rollup + per-instance views machine-readably for scripting/CI)."""
    from dataclasses import asdict

    from .telemetry.fleet import FleetAggregator, render_top

    while True:
        view = await FleetAggregator.scrape_runtime(drt)
        if getattr(args, "json", False):
            print(
                json.dumps(
                    {
                        "rollup": view.rollup(),
                        "instances": {
                            name: asdict(m)
                            for name, m in sorted(view.members.items())
                        },
                        "missing": dict(view.missing),
                    },
                    indent=2,
                )
            )
            return 0
        body = render_top(view)
        if args.once:
            print(body)
            return 0
        # Cursor-home clear keeps the refresh loop flicker-free on a
        # bare terminal without a curses dependency.
        print("\x1b[2J\x1b[H" + body, flush=True)
        await asyncio.sleep(max(args.interval, 0.2))


def run_sim(args) -> int:
    from .planner import PlannerConfig, SloTargets
    from .sim import (
        ClusterSim,
        ServiceTimeModel,
        SimConfig,
        burst_workload,
        diurnal_workload,
        load_trace,
        ramp_workload,
        save_trace,
        synthetic_users,
    )

    if args.trace_in:
        workload = load_trace(args.trace_in)
    elif args.workload == "burst":
        workload = burst_workload(args.seed, n=args.requests or 8)
    elif args.workload == "ramp":
        workload = ramp_workload(
            args.seed,
            duration_s=args.duration_s,
            rps_start=args.rps_start,
            rps_end=args.rps_end,
        )
    elif args.workload == "diurnal":
        workload = diurnal_workload(
            args.seed,
            duration_s=args.duration_s,
            rps_base=args.rps_start,
            rps_peak=args.rps_end,
            period_s=args.period_s,
        )
    else:
        workload = synthetic_users(
            args.seed,
            users=args.requests or 100_000,
            duration_s=args.duration_s,
        )
    if args.prefix_groups > 0:
        # Shared-prefix fleet mix (docs/prefix_sharing.md): arrivals
        # draw a group seeded independently of the arrival process, so
        # adding groups never perturbs arrival times.
        import random as _random
        from dataclasses import replace as _replace

        grng = _random.Random(args.seed ^ 0x9EF1)
        workload = [
            _replace(
                r,
                prefix_group=grng.randrange(args.prefix_groups),
                prefix_len=min(
                    args.prefix_len or max(r.prompt_len // 2, 1),
                    r.prompt_len,
                ),
            )
            for r in workload
        ]
    if args.trace_out:
        workload = list(workload)
        n = save_trace(args.trace_out, workload)
        print(f"# {n} requests -> {args.trace_out}", file=sys.stderr)
    service = (
        ServiceTimeModel.from_telemetry(
            span_paths=args.fit_spans, bench_paths=args.fit_bench
        )
        if (args.fit_spans or args.fit_bench)
        else ServiceTimeModel.default()
    )
    cfg = SimConfig(
        seed=args.seed,
        slots_per_instance=args.slots,
        pages_per_instance=args.pages,
        page_size=args.page_size,
        max_inflight=args.max_inflight,
        shed_watermark=args.shed_watermark,
        admission_per_instance=args.planner != "none",
        initial_instances=args.instances,
        provision_s=args.provision_s,
        planner=None if args.planner == "none" else args.planner,
        planner_cfg=PlannerConfig(
            max_tpu_budget=args.max_tpu_budget, min_endpoint=1
        ),
        slo=SloTargets(
            ttft_p99_slo_s=args.ttft_slo_s,
            itl_p99_slo_s=args.itl_slo_s,
            # Fitted-service hint: scale for where the trend will be
            # when a new worker actually lands. A measured cold/warm
            # provision (bench.py --coldstart-sweep via --fit-bench, or
            # the --provision-s study knob) flows in here (docs/aot.md).
            provision_s=(
                args.provision_s
                if args.provision_s is not None
                else service.planner_hints()["provision_s"]
            ),
        ),
        service=service,
        record_events=args.events,
        prefix_sharing=not args.no_prefix_sharing,
        host_pages_per_instance=args.host_pages,
        kv_packing=not args.no_kv_packing,
        g3_pages_per_instance=args.g3_pages,
        restart_at_s=args.restart_at_s,
    )
    sim = ClusterSim(cfg, workload)
    report = sim.run()
    if args.events:
        # Event lines own stdout (grep/diff-able stream, as the flag's
        # help promises); the report rides stderr so it's still visible
        # without corrupting either consumer.
        for line in sim.event_log:
            print(line)
        print(report.to_json(indent=2), file=sys.stderr)
    else:
        print(report.to_json(indent=2))
    return 0


async def drain_instance(drt, args) -> int:
    from .runtime.component import DRAIN_PREFIX

    live = {
        i.instance_id
        for i in await drt.discovery.list_instances("")
    }
    if args.instance_id not in live:
        print(f"instance {args.instance_id} is not live", file=sys.stderr)
        return 1
    await drt.discovery.kv_put(f"{DRAIN_PREFIX}{args.instance_id}", b"1")
    print(
        f"drain requested for instance {args.instance_id}; routers stop "
        "sending new work once the worker republishes its metadata"
    )
    return 0


async def reclaim_instance(drt, args) -> int:
    from .runtime.component import RECLAIM_PREFIX

    live = {
        i.instance_id
        for i in await drt.discovery.list_instances("")
    }
    if args.instance_id not in live:
        print(f"instance {args.instance_id} is not live", file=sys.stderr)
        return 1
    payload = json.dumps({"grace_s": args.grace_s}).encode()
    await drt.discovery.kv_put(
        f"{RECLAIM_PREFIX}{args.instance_id}", payload
    )
    print(
        f"reclaim notice sent to instance {args.instance_id} "
        f"(grace {args.grace_s:g}s); in-flight sequences triage into "
        "live migration or journal failover under the deadline"
    )
    return 0


async def get_disagg(drt, args) -> int:
    from .disagg.config import DisaggConfig, disagg_config_key

    raw = await drt.discovery.kv_get(disagg_config_key(args.model_name))
    cfg = DisaggConfig.from_bytes(raw) if raw else DisaggConfig()
    print(json.dumps({"model": args.model_name, **cfg.__dict__}, indent=2))
    return 0


async def set_disagg(drt, args) -> int:
    from .disagg.config import DisaggConfig, disagg_config_key

    cfg = DisaggConfig(
        max_local_prefill_length=args.max_local_prefill_length,
        max_prefill_queue_size=args.max_prefill_queue_size,
    )
    await drt.discovery.kv_put(disagg_config_key(args.model_name), cfg.to_bytes())
    print(f"disagg config for {args.model_name} updated: {cfg}")
    return 0


async def run(args) -> int:
    from .runtime.component import DistributedRuntime
    from .runtime.config import RuntimeConfig

    if args.plane == "trace":  # offline: reads recorder files, no cluster
        return run_trace(args)
    if args.plane == "flight":  # offline: reads flight dumps, no cluster
        return run_flight(args)
    if args.plane == "audit":  # offline: reads flight dumps, no cluster
        return run_audit(args)
    if args.plane == "bench":  # offline: reads bench captures, no cluster
        return run_bench_compare(args)
    if args.plane == "sim":  # offline: modeled fleet, no cluster
        return run_sim(args)
    if args.plane == "fingerprint":  # offline: reads recorded files
        return run_fingerprint(args)
    if args.plane == "slow" and not args.coordinator:
        # Offline over a span file; with --coordinator it scrapes the
        # fleet's exemplar rings below instead.
        return run_slow_offline(args)
    if args.plane == "aot":  # offline: compile lattice, no cluster
        return await run_aot(args)
    if args.plane == "tune":  # offline: sim search (+ local tiny harness)
        return await run_tune(args)
    if args.plane == "lint":  # offline: AST checks, no cluster
        from .analysis.runner import run_cli

        return run_cli(args)
    if not args.coordinator:
        print("--coordinator is required for this command", file=sys.stderr)
        return 2
    drt = DistributedRuntime(
        config=RuntimeConfig(coordinator_endpoint=args.coordinator)
    )
    try:
        if args.plane == "top":
            return await run_top(drt, args)
        if args.plane == "slow":
            return await run_slow_live(drt, args)
        if args.plane == "drain":
            return await drain_instance(drt, args)
        if args.plane == "reclaim":
            return await reclaim_instance(drt, args)
        if args.plane == "disagg":
            if args.command == "get":
                return await get_disagg(drt, args)
            return await set_disagg(drt, args)
        if args.command == "add":
            return await add_model(drt, args)
        if args.command == "list":
            return await list_models(drt, args)
        return await remove_model(drt, args)
    finally:
        await drt.close()


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
