"""Rotary position embeddings (Llama-style, with Llama-3 rope scaling).

TPU notes: computed in float32 then cast back — RoPE precision matters for
long context, and the VPU handles the elementwise work fused into the
surrounding matmuls by XLA.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, theta: float, scaling: dict | None = None
) -> jnp.ndarray:
    """Inverse frequencies [head_dim/2], with optional llama3 scaling."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if not scaling:
        return inv_freq
    kind = scaling.get("rope_type", scaling.get("type"))
    if kind in (None, "default"):
        return inv_freq
    if kind == "linear":
        return inv_freq / scaling["factor"]
    if kind != "llama3":
        raise ValueError(f"unsupported rope_scaling type: {kind!r}")
    factor = scaling["factor"]
    low = scaling.get("low_freq_factor", 1.0)
    high = scaling.get("high_freq_factor", 4.0)
    old_ctx = scaling.get("original_max_position_embeddings", 8192)
    wavelen = 2 * math.pi / inv_freq
    low_wl = old_ctx / low
    high_wl = old_ctx / high
    smooth = (old_ctx / wavelen - low) / (high - low)
    return jnp.where(
        wavelen > low_wl,
        inv_freq / factor,
        jnp.where(
            wavelen < high_wl,
            inv_freq,
            (1 - smooth) * inv_freq / factor + smooth * inv_freq,
        ),
    )


def apply_rope(
    x: jnp.ndarray, positions: jnp.ndarray, inv_freq: jnp.ndarray
) -> jnp.ndarray:
    """Rotate ``x``: [..., T, H, D] by per-token ``positions``: [..., T].

    Uses the HF "half-split" convention (rotate_half), matching Llama
    checkpoints: pairs are (x[i], x[i + D/2]).
    """
    dtype = x.dtype
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., T, D/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., T, 1, D/2]
    sin = jnp.sin(angles)[..., None, :]
    xf = x.astype(jnp.float32)
    half = xf.shape[-1] // 2
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(dtype)
