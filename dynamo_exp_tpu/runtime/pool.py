"""Returnable object pool with RAII-style return-on-release.

The basis of the KV block pool: items checked out of the pool return to it
when released (or garbage-collected), and waiters are woken in order.

Reference capability: ``/root/reference/lib/runtime/src/utils/pool.rs:89-427``.
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class PoolItem(Generic[T]):
    """A checked-out pool item; ``release()`` (or ``with``, or garbage
    collection of a dropped item) returns it to the pool."""

    def __init__(self, value: T, pool: "Pool[T]"):
        self._value = value
        self._pool: Pool[T] | None = pool

    @property
    def value(self) -> T:
        if self._pool is None:
            raise RuntimeError("pool item used after release")
        return self._value

    def release(self) -> None:
        if self._pool is not None:
            pool, self._pool = self._pool, None
            pool._return(self._value)

    def __enter__(self) -> T:
        return self.value

    def __exit__(self, *exc) -> None:
        self.release()

    def __del__(self) -> None:
        # RAII backstop: a dropped item (e.g. on an exception path) must
        # not permanently shrink pool capacity.
        self.release()


class Pool(Generic[T]):
    """Fixed-capacity async pool. ``acquire`` waits until an item is free."""

    def __init__(self, items: list[T], on_return: Callable[[T], None] | None = None):
        self._free: collections.deque[T] = collections.deque(items)
        self._capacity = len(items)
        self._on_return = on_return
        self._waiters: collections.deque[asyncio.Future] = collections.deque()

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def available(self) -> int:
        return len(self._free)

    def try_acquire(self) -> PoolItem[T] | None:
        if self._free:
            return PoolItem(self._free.popleft(), self)
        return None

    async def acquire(self) -> PoolItem[T]:
        item = self.try_acquire()
        if item is not None:
            return item
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            value = await fut
        except asyncio.CancelledError:
            # If the value was already handed to us, re-offer it so the
            # item isn't leaked (asyncio.Queue-style cancellation safety).
            # on_return already ran for this value; don't run it again.
            if fut.done() and not fut.cancelled():
                self._offer(fut.result())
            else:
                with contextlib.suppress(ValueError):
                    self._waiters.remove(fut)
            raise
        return PoolItem(value, self)

    def _return(self, value: T) -> None:
        if self._on_return is not None:
            self._on_return(value)
        self._offer(value)

    def _offer(self, value: T) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(value)
                return
        self._free.append(value)
