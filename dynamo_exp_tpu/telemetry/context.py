"""Trace context: one (trace_id, span_id) pair flowing with each request.

The reference gets request correlation from its tracing subscriber
(``/root/reference/lib/runtime/src/logging.rs`` span fields in JSONL
logs); here the equivalent is a contextvar carrying the current trace
coordinates. Everything async inside one request shares the var (tasks
snapshot their parent's context), and the seams that leave the
process/task — the TCP request plane, the prefill work queue, the KV
transfer plane, the engine loop thread — carry it explicitly as a tiny
wire dict (``to_wire``/``from_wire``) or a captured ``TraceContext``.
"""

from __future__ import annotations

import contextvars
import uuid
from dataclasses import dataclass


@dataclass(frozen=True)
class TraceContext:
    """Coordinates of the *current* span: children parent onto span_id."""

    trace_id: str
    span_id: str

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_id())

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "parent_span_id": self.span_id}

    @staticmethod
    def from_wire(d: dict | None) -> "TraceContext | None":
        if not d or not d.get("trace_id"):
            return None
        return TraceContext(d["trace_id"], d.get("parent_span_id", ""))


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "dynamo_trace_context", default=None
)


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


def current_trace() -> TraceContext | None:
    """The active trace context, or None outside any traced request."""
    return _current.get()


def current_trace_id() -> str | None:
    tc = _current.get()
    return tc.trace_id if tc is not None else None


def current_span_id() -> str | None:
    tc = _current.get()
    return tc.span_id if tc is not None else None


def new_trace(trace_id: str | None = None) -> TraceContext:
    """A fresh root context (``span_id`` is the root span's id)."""
    return TraceContext(trace_id or uuid.uuid4().hex, _new_id())


def attach(tc: TraceContext | None) -> contextvars.Token:
    """Make ``tc`` current; pass the returned token to :func:`detach`."""
    return _current.set(tc)


def detach(token: contextvars.Token) -> None:
    _current.reset(token)


def wire_headers() -> dict:
    """The current context as a wire dict, or {} when untraced — for
    merging into transport headers."""
    tc = _current.get()
    return tc.to_wire() if tc is not None else {}
