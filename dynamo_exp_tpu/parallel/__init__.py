from .mesh import build_mesh, largest_tp, shard, shard_pytree, single_device_mesh

__all__ = [
    "build_mesh",
    "single_device_mesh",
    "shard",
    "shard_pytree",
    "largest_tp",
]
