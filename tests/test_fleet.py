"""Fleet observability plane (docs/observability.md "Fleet plane"):
FleetAggregator rollups, scrape fault tolerance, the per-link
TransferLedger, `llmctl top`/`bench compare`, the multi-instance trace
timeline, and the live↔sim fleet-rollup mirror."""

from __future__ import annotations

import asyncio
import json
import time

import pytest

from dynamo_exp_tpu import llmctl
from dynamo_exp_tpu.telemetry import Span
from dynamo_exp_tpu.telemetry.bench_compare import (
    compare_bench,
    load_bench_lines,
    render_compare,
)
from dynamo_exp_tpu.telemetry.fleet import (
    FleetAggregator,
    FleetView,
    InstanceView,
    TransferLedger,
    parse_prometheus_text,
    render_top,
)
from dynamo_exp_tpu.telemetry.timeline import render_timeline, transfer_hops

pytestmark = pytest.mark.pre_merge


def _metrics(name="w", running=2, waiting=1, occ=0.5, **extra) -> dict:
    return {
        "num_requests_running": running,
        "num_requests_waiting": waiting,
        "gpu_cache_usage_perc": occ,
        "request_active_slots": running,
        "request_total_slots": 8,
        "preemptions": extra.pop("preemptions", 0),
        "kv_ledger_violations": extra.pop("violations", 0),
        "build_info": extra.pop(
            "build_info",
            {"manifest_hash": "abc", "jax_version": "0.4",
             "prefix_sharing": True, "spec": "off"},
        ),
        **extra,
    }


# ------------------------------------------------------------ transfer ledger
def test_ledger_records_links_and_estimates_bandwidth():
    led = TransferLedger()
    # 1 MB in 0.1 s = 10 MB/s on a->b; 2 MB in 0.1 s = 20 MB/s on a->c.
    led.record("a", "b", 1 << 20, 0.1)
    led.record("a", "c", 2 << 20, 0.1)
    bw_ab = led.bandwidth_bps("a", "b")
    assert bw_ab == pytest.approx((1 << 20) / 0.1)
    assert led.estimate_transfer_s("a", "c", 2 << 20) == pytest.approx(0.1)
    assert led.bandwidth_bps("a", "zz") is None
    # Never-observed links price at the cold-start prior (reclaim triage
    # must cost transfers on a fresh fleet); only a disabled prior
    # leaves them unpriceable — tests/test_reclaim.py covers the knob.
    assert led.estimate_transfer_s("a", "zz", 100) == pytest.approx(
        100 / led.default_bandwidth_bps
    )
    assert TransferLedger(default_bandwidth_bps=0).estimate_transfer_s(
        "a", "zz", 100
    ) is None
    # EWMA: a second, slower observation moves the estimate toward it
    # without erasing the history.
    led.record("a", "b", 1 << 20, 0.2)
    bw2 = led.bandwidth_bps("a", "b")
    assert (1 << 20) / 0.2 < bw2 < bw_ab
    snap = led.snapshot()
    assert [(s["src"], s["dst"]) for s in snap] == [("a", "b"), ("a", "c")]
    assert snap[0]["transfers"] == 2
    # Degenerate observations count the transfer, not the bandwidth.
    led.record("a", "b", 0, 0.0)
    assert led.bandwidth_bps("a", "b") == pytest.approx(bw2)


def test_ledger_mirrors_prometheus_link_series():
    from dynamo_exp_tpu.telemetry import get_telemetry, get_transfer_ledger

    led = get_transfer_ledger()
    led.record("src-x", "dst-y", 4096, 0.01)
    text = get_telemetry().render().decode()
    assert 'dynamo_kv_link_bytes_total{dst="dst-y",src="src-x"}' in text
    assert "dynamo_kv_link_bandwidth_bytes_per_s" in text


# ------------------------------------------------------------- fleet view
def test_fleet_view_rollup_and_skew():
    view = FleetView.from_snapshots(
        {
            "w0": _metrics(running=2, waiting=1, occ=0.5),
            "w1": _metrics(running=3, waiting=0, occ=0.7),
            "w2": _metrics(
                running=1, waiting=4, occ=0.1,
                build_info={"manifest_hash": "OTHER", "jax_version": "0.4",
                            "prefix_sharing": True, "spec": "off"},
            ),
        }
    )
    roll = view.rollup()
    assert roll["instances"] == 3
    assert roll["running"] == 6 and roll["waiting"] == 5
    assert roll["occupancy_mean"] == round((0.5 + 0.7 + 0.1) / 3, 4)
    # The odd-one-out fingerprint is flagged, not the majority.
    assert roll["config_skew"] == ["w2"]
    assert "SKEW" in render_top(view)


def test_fleet_scrape_fault_tolerance():
    """Satellite acceptance: an instance dying or returning garbage
    mid-scrape yields a fleet view tagged with the missing member —
    never an exception, never a poisoned rollup."""
    healthy = _metrics(running=2, waiting=1, occ=0.5)

    async def dead():
        raise ConnectionError("instance died mid-scrape")

    def garbage():
        return "}{ not metrics"

    async def nan_fields():
        # Numeric garbage inside an otherwise-dict snapshot: fields
        # degrade to defaults, the member stays healthy.
        return {"num_requests_running": "NaN-ish", "gpu_cache_usage_perc": None}

    agg = FleetAggregator(
        {
            "good": lambda: dict(healthy),
            "dead": dead,
            "garbage": garbage,
            "weird": nan_fields,
        }
    )
    view = asyncio.run(agg.scrape())
    assert set(view.members) == {"good", "weird"}
    assert set(view.missing) == {"dead", "garbage"}
    assert "died mid-scrape" in view.missing["dead"]
    roll = view.rollup()
    assert roll["running"] == 2  # garbage contributed nothing
    assert roll["missing"] == ["dead", "garbage"]
    body = render_top(view)
    assert "MISSING" in body and "dead" in body


def test_fleet_view_from_prometheus_text():
    text = """
# HELP dynamo_engine_num_requests_running Sequences actively decoding
dynamo_engine_num_requests_running 3.0
dynamo_engine_num_requests_waiting 2.0
dynamo_engine_hbm_page_occupancy 0.25
dynamo_requests_shed_total{priority="low",code="429"} 4.0
dynamo_requests_shed_total{priority="high",code="503"} 1.0
dynamo_kv_ledger_violations_total 0.0
dynamo_build_info{manifest_hash="mh1",jax_version="0.4",prefix_sharing="true",spec="off"} 1.0
garbage line that parses to nothing
{"not": "prometheus"}
"""
    parsed = parse_prometheus_text(text)
    view = InstanceView.from_metrics("edge", parsed)
    assert view.running == 3 and view.waiting == 2
    assert view.occupancy == pytest.approx(0.25)
    assert view.shed == 5  # summed across label sets
    assert view.ledger_violations == 0
    # build_info's fingerprint lives in its LABELS — the parser must
    # surface them so text-scraped members join skew detection.
    assert view.build_info["manifest_hash"] == "mh1"


def test_parse_prometheus_text_handles_exposition_timestamps():
    """The optional trailing timestamp (federation/pushgateway output)
    must never be mistaken for the value or drop the sample."""
    text = (
        'dynamo_preemptions_total 3 1722700000000\n'
        'dynamo_kv_link_bytes_total{src="a",dst="b"} 123 1722700000000\n'
    )
    parsed = parse_prometheus_text(text)
    assert parsed["dynamo_preemptions_total"] == 3.0
    assert parsed["dynamo_kv_link_bytes_total"] == 123.0


def test_parse_prometheus_text_brace_in_label_value_and_fallback():
    """A '}' inside a quoted label value must not break the sample, and
    a payload the strict parser rejects falls back to lenient per-line
    parsing instead of discarding the healthy lines."""
    text = (
        'dynamo_build_info{manifest_hash="m}1",jax_version="0.4",'
        'prefix_sharing="true",spec="off"} 1.0\n'
        "dynamo_engine_num_requests_running 2\n"
        "!!! this line is garbage !!!\n"
    )
    parsed = parse_prometheus_text(text)
    assert parsed["dynamo_engine_num_requests_running"] == 2.0
    assert parsed["build_info"]["manifest_hash"] == "m}1"


def test_config_skew_ignores_members_without_build_info():
    """A member whose scrape surface carries no build_info at all is
    *unknown*, not skewed — a mixed stats-plane/text fleet must not
    light up red."""
    view = FleetView.from_snapshots(
        {
            "w0": _metrics(),
            "w1": _metrics(),
            "edge": {"num_requests_running": 1, "build_info": {}},
        }
    )
    assert view.config_skew() == []


def test_merged_links_rollup_is_duration_weighted():
    view = FleetView.from_snapshots(
        {
            "w0": _metrics(kv_links=[
                {"src": "a", "dst": "b", "transfers": 1, "bytes": 1000,
                 "duration_s": 1.0, "bandwidth_bps": 1000.0},
            ]),
            "w1": _metrics(kv_links=[
                {"src": "a", "dst": "b", "transfers": 3, "bytes": 3000,
                 "duration_s": 1.0, "bandwidth_bps": 3000.0},
            ]),
        }
    )
    (link,) = view.rollup()["links"]
    assert link["transfers"] == 4 and link["bytes"] == 4000
    assert link["bandwidth_bps"] == pytest.approx(2000.0)
    assert "a -> b" in render_top(view)


def test_llmctl_top_once_over_fake_runtime(capsys):
    """`llmctl top --once` walks discovery, scrapes each instance's
    stats plane, tags the dead one, and prints a single dashboard."""

    class _Addr:
        component = "TpuWorker"

    class _Info:
        def __init__(self, iid, draining=False):
            self.address = _Addr()
            self.instance_id = iid
            self.metadata = {"draining": True} if draining else {}

    class _Discovery:
        async def list_instances(self, _prefix):
            return [_Info(1), _Info(2, draining=True), _Info(3)]

    class _Plane:
        async def scrape_stats(self, info):
            if info.instance_id == 3:
                raise ConnectionError("gone")
            return _metrics(running=info.instance_id)

    class _Drt:
        discovery = _Discovery()
        request_plane = _Plane()

    class _Args:
        once = True
        interval = 2.0

    rc = asyncio.run(llmctl.run_top(_Drt(), _Args()))
    out = capsys.readouterr().out
    assert rc == 0
    assert "TpuWorker/1" in out and "TpuWorker/3" in out
    assert "MISSING" in out and "draining" in out


# --------------------------------------------------- multi-instance timeline
def _span(stage, trace, start, end, parent="", **attrs):
    return Span(
        stage=stage, trace_id=trace, span_id=f"{stage}-{start}",
        parent_span_id=parent, start=start, end=end, attrs=attrs,
    )


def test_render_timeline_multi_instance_with_transfer_hops():
    t = 1000.0
    spans = [
        _span("http_request", "T", t, t + 1.0, instance="decode-0",
              request_id="r1"),
        _span("remote_prefill", "T", t + 0.1, t + 0.6,
              parent="http_request-1000.0", instance="decode-0"),
        _span("prefill", "T", t + 0.15, t + 0.4, instance="prefill-0"),
        _span("kv_transfer_send", "T", t + 0.4, t + 0.5,
              instance="prefill-0", src="prefill-0", dst="decode-0",
              bytes=2 << 20),
        _span("kv_transfer_recv", "T", t + 0.41, t + 0.5,
              instance="decode-0", src="prefill-0", dst="decode-0",
              bytes=2 << 20),
        _span("kv_lease", "T", t + 0.35, t + 0.52, instance="prefill-0",
              outcome="confirmed"),
    ]
    out = render_timeline(spans)
    assert "across 2 instances" in out
    assert "[prefill-0" in out and "[decode-0" in out
    assert "transfer hops:" in out
    assert "prefill-0 -> decode-0" in out
    assert "MB/s" in out
    hops = transfer_hops(spans)
    assert len(hops) == 2
    assert hops[0]["stage"] == "kv_transfer_send"
    assert hops[0]["duration_s"] == pytest.approx(0.1)
    # Single-instance traces keep the compact label format.
    solo = [_span("decode", "S", t, t + 1, instance="only")]
    assert "[only]" not in render_timeline(solo)


# ------------------------------------------------------------ bench compare
def _bench_line(metric, value=100.0, unit="tok/s", platform="cpu", **extra):
    return {"metric": metric, "value": value, "unit": unit,
            "platform": platform, **extra}


def test_bench_compare_flags_regressions_and_improvements():
    old = [_bench_line("decode_tp", 100.0, p99_ttft_s=1.0),
           _bench_line("other", 50.0)]
    new = [_bench_line("decode_tp", 80.0, p99_ttft_s=1.5),
           _bench_line("other", 60.0)]
    rep = compare_bench(old, new, threshold=0.10)
    assert not rep.ok
    kinds = {(f.field, f.kind) for f in rep.findings}
    assert ("value(tok/s)", "regression") in kinds
    assert ("p99_ttft_s", "regression") in kinds
    assert ("value(tok/s)", "improvement") in kinds
    text = render_compare(rep, "a.json", "b.json")
    assert "REGRESSION" in text


def test_bench_compare_is_platform_tag_aware():
    """A chip line never compares against a CPU-fallback line — the
    pair is skipped with a note, not flagged."""
    old = [_bench_line("decode_tp", 500.0, platform="tpu")]
    new = [_bench_line("decode_tp", 50.0, platform="cpu")]
    rep = compare_bench(old, new)
    assert rep.ok and rep.compared == 0
    assert any("not comparable" in s for s in rep.skipped)


def test_bench_compare_wrapper_and_jsonl_formats(tmp_path):
    wrapper = {
        "n": 9, "cmd": "bench", "rc": 0,
        "tail": 'noise\n{"metric": "m1", "value": 10.0, "unit": "tok/s", '
                '"platform": "cpu"}\nnot json {',
        "parsed": {"metric": "m0", "value": 5.0, "unit": "tok/s",
                   "platform": "cpu"},
    }
    a = tmp_path / "a.json"
    a.write_text(json.dumps(wrapper))
    lines = load_bench_lines(str(a))
    assert {ln["metric"] for ln in lines} == {"m0", "m1"}
    b = tmp_path / "b.jsonl"
    b.write_text(
        '{"metric": "m1", "value": 8.0, "unit": "tok/s", "platform": "cpu"}\n'
    )
    rep = compare_bench(load_bench_lines(str(a)), load_bench_lines(str(b)))
    assert not rep.ok  # 10 -> 8 is a 20% drop


def test_bench_compare_cli_over_checked_in_trajectory(capsys):
    """The pre-merge CI step: comparing the checked-in BENCH_r*.json
    files must exit 0 — failed runs (TPU tunnel down) yield no
    comparable pairs and compare clean, platform-aware by design."""
    import os

    repo = os.path.join(os.path.dirname(__file__), "..")
    r04, r05 = (os.path.join(repo, f"BENCH_r0{n}.json") for n in (4, 5))
    rc = llmctl.main(["bench", "compare", r04, r05])
    out = capsys.readouterr().out
    assert rc == 0
    assert "no comparable metrics" in out or "no regressions" in out


def test_bench_compare_cli_exit_codes(tmp_path, capsys):
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    a.write_text(json.dumps(_bench_line("m", 100.0)) + "\n")
    b.write_text(json.dumps(_bench_line("m", 50.0)) + "\n")
    assert llmctl.main(["bench", "compare", str(a), str(b)]) == 1
    assert llmctl.main(["bench", "compare", str(a), str(a)]) == 0
    capsys.readouterr()
    assert llmctl.main(["bench", "compare", str(a), "/nope.json"]) == 2


# --------------------------------------------------------------- sim mirror
@pytest.mark.sim
def test_sim_report_fleet_rollup_mirrors_live_shape():
    """`SimReport.fleet` is built through the SAME FleetView.rollup()
    path the live aggregator uses — identical keys, deterministic
    across same-seed runs."""
    from dynamo_exp_tpu.sim import ClusterSim, SimConfig, burst_workload

    def run():
        cfg = SimConfig(seed=7, initial_instances=2, record_events=False)
        return ClusterSim(cfg, burst_workload(7, n=6)).run()

    r1, r2 = run(), run()
    assert r1.fleet == r2.fleet  # deterministic
    live_keys = set(
        FleetView.from_snapshots({"w": _metrics()}).rollup().keys()
    )
    assert set(r1.fleet.keys()) == live_keys
    assert r1.fleet["instances"] == 2
    assert r1.fleet["missing"] == [] and r1.fleet["config_skew"] == []
    # to_dict round-trips with the fleet block included.
    assert json.loads(r1.to_json())["fleet"] == r1.fleet


def test_instance_view_handles_draining_and_violations():
    view = FleetView.from_snapshots(
        {"w0": _metrics(draining=True, violations=2)}
    )
    m = view.members["w0"]
    assert m.draining and m.ledger_violations == 2
    body = render_top(view)
    assert "draining" in body and "LEDGER!2" in body
    assert view.rollup()["ledger_violations"] == 2


def test_fleet_view_scrape_timestamp_never_enters_rollup():
    """The rollup must stay wall-clock-free (the sim mirrors it into
    seeded regression diffs)."""
    v1 = FleetView.from_snapshots({"w": _metrics()})
    time.sleep(0.01)
    v2 = FleetView.from_snapshots({"w": _metrics()})
    assert v1.scraped_at != v2.scraped_at
    assert v1.rollup() == v2.rollup()


def test_render_top_empty_fleet():
    view = FleetView.from_snapshots({})
    body = render_top(view)
    assert "0 instance(s)" in body
