"""KV-aware router: index correctness, selection policy, and end-to-end
routing over the in-proc runtime with engine-published events."""

import asyncio

import numpy as np
import pytest

from dynamo_exp_tpu.kv_router import (
    DefaultWorkerSelector,
    ForwardPassMetrics,
    KvCacheEventData,
    KvEventPublisher,
    KvIndexer,
    KvRouter,
    NoWorkersError,
    OverlapScores,
    ProcessedEndpoints,
    RadixIndex,
    RouterEvent,
)
from dynamo_exp_tpu.runtime.component import DistributedRuntime
from dynamo_exp_tpu.tokens import compute_block_hashes_for_seq


def ev(worker, kind, hashes, parent=None):
    return RouterEvent(worker, KvCacheEventData(kind, list(hashes), parent))


def test_radix_index_contiguous_prefix_matching():
    idx = RadixIndex()
    toks = list(range(1, 33))
    hashes = compute_block_hashes_for_seq(toks, 8)  # 4 blocks
    idx.apply_event(ev(1, "stored", hashes[:3]))
    idx.apply_event(ev(2, "stored", hashes[:1]))
    # Worker 3 holds blocks 2-3 but NOT the start: must score 0.
    idx.apply_event(ev(3, "stored", hashes[2:]))

    scores = idx.find_matches(hashes).scores
    assert scores == {1: 3, 2: 1}

    idx.apply_event(ev(1, "removed", [hashes[1]]))
    assert idx.find_matches(hashes).scores == {1: 1, 2: 1}

    idx.remove_worker(1)
    assert idx.find_matches(hashes).scores == {2: 1}


def test_selector_prefers_overlap_then_load():
    sel = DefaultWorkerSelector()
    eps = ProcessedEndpoints(
        metrics={
            1: ForwardPassMetrics(request_active_slots=0, request_total_slots=8),
            2: ForwardPassMetrics(request_active_slots=0, request_total_slots=8),
        }
    )
    # Worker 2 has 4 of 8 blocks cached (isl 64, bs 8): overlap wins.
    wid, overlap = sel.select_worker(eps, OverlapScores({2: 4}), 64, 8)
    assert (wid, overlap) == (2, 4)

    # Same overlap, worker 1 heavily loaded -> worker 2.
    eps.metrics[1].request_active_slots = 8
    eps.metrics[1].gpu_cache_usage_perc = 0.9
    wid, _ = sel.select_worker(eps, OverlapScores({1: 2, 2: 2}), 64, 8)
    assert wid == 2

    # Big overlap beats moderate load difference (2*overlap term).
    eps2 = ProcessedEndpoints(
        metrics={
            1: ForwardPassMetrics(
                request_active_slots=4, request_total_slots=8,
                gpu_cache_usage_perc=0.5,
            ),
            2: ForwardPassMetrics(request_active_slots=0, request_total_slots=8),
        }
    )
    wid, _ = sel.select_worker(eps2, OverlapScores({1: 8}), 64, 8)
    assert wid == 1  # 2*1.0 - 0.5 - 0.5 = 1.0 > 0.0

    with pytest.raises(NoWorkersError):
        sel.select_worker(ProcessedEndpoints(), OverlapScores(), 10, 8)


async def test_kv_router_end_to_end_over_runtime():
    """Two fake workers serve via the in-proc runtime; KV events flow over
    the event plane; the router sends a warm request to the cache holder."""
    drt = DistributedRuntime.detached()
    comp = drt.namespace("test").component("backend")

    stats = {
        "w1": ForwardPassMetrics(request_total_slots=8),
        "w2": ForwardPassMetrics(request_total_slots=8),
    }

    async def handler(request, ctx):
        yield {"ok": True}

    i1 = await comp.endpoint("generate").serve_endpoint(
        handler, stats_handler=lambda: stats["w1"].to_dict()
    )
    i2 = await comp.endpoint("generate").serve_endpoint(
        handler, stats_handler=lambda: stats["w2"].to_dict()
    )

    router = KvRouter(comp, block_size=8, scrape_interval_s=0.01)
    await router.start()

    toks = list(np.random.RandomState(0).randint(1, 100, size=32))
    hashes = compute_block_hashes_for_seq(toks, 8)

    pub1 = KvEventPublisher(
        drt.event_plane, comp.path, worker_id=i1.instance_id,
        loop=asyncio.get_running_loop(),
    )
    await pub1.publish(KvCacheEventData("stored", hashes))
    await asyncio.sleep(0.05)  # let the indexer pump apply it

    resp = await router.schedule(toks)
    assert resp.worker_id == i1.instance_id
    assert resp.overlap_blocks == 4

    # Cold request (no overlap anywhere): both workers equally idle —
    # any choice is fine; with w1 loaded it must pick w2.
    stats["w1"].request_active_slots = 8
    await asyncio.sleep(0.05)  # aggregator picks up the new stats
    cold = list(np.random.RandomState(9).randint(100, 200, size=32))
    resp2 = await router.schedule(cold)
    assert resp2.worker_id == i2.instance_id

    await router.stop()
    await i1.close()
    await i2.close()
    await drt.close()


async def test_engine_events_reach_router_index():
    """Real tiny engine -> KvEventPublisher -> event plane -> KvIndexer."""
    from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
    from dynamo_exp_tpu.models import TINY
    from dynamo_exp_tpu.parallel import single_device_mesh
    from dynamo_exp_tpu.protocols.common import BackendInput

    drt = DistributedRuntime.detached()
    pub = KvEventPublisher(
        drt.event_plane, "test.backend", worker_id=7,
        loop=asyncio.get_running_loop(),
    )
    indexer = KvIndexer(block_size=8)
    await indexer.start(drt.event_plane, "test.backend.kv_events")

    cfg = EngineConfig(
        model=TINY, max_decode_slots=2, page_size=8, num_pages=32,
        max_model_len=64, eos_token_ids=[],
    )
    eng = TPUEngine(cfg, mesh=single_device_mesh(), kv_event_cb=pub.engine_callback())
    eng.start()
    try:
        prompt = list(np.random.RandomState(3).randint(3, 200, size=17))
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 4
        b.stop_conditions.ignore_eos = True
        stream = await eng.generate(b.to_dict())
        async for _ in stream:
            pass
        await asyncio.sleep(0.1)
        scores = indexer.find_matches_for_request(prompt)
        assert scores.scores.get(7, 0) >= 2  # both full prompt pages indexed
    finally:
        eng.stop()
        await indexer.stop()
        await drt.close()
