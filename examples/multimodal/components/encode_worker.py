"""EncodeWorker: image → embedding service of the multimodal graph.

Reference parity:
``/root/reference/examples/multimodal/components/encode_worker.py:21-60``
(vision tower + projector on its own GPU, streaming image features to
the LLM worker). TPU-native: a JAX patch encoder — patchify, linear
projection, one attention-free mixing layer — standing in for a full
vision tower; the seam it feeds (``image_features`` consumed as soft
tokens via ``models/llama.forward(token_embeds=...)``) is the real one.
"""

from __future__ import annotations

import base64
import logging

import numpy as np

from dynamo_exp_tpu.sdk import async_on_start, endpoint, service

logger = logging.getLogger(__name__)


class PatchEncoder:
    """Patchify [H, W, 3] → project each patch to the LM hidden size."""

    def __init__(self, hidden_size: int, patch: int = 16, seed: int = 0):
        import jax
        import jax.numpy as jnp

        self.patch = patch
        self.hidden = hidden_size
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        in_dim = patch * patch * 3
        self.w_proj = jax.random.normal(
            k1, (in_dim, hidden_size), jnp.float32
        ) * (in_dim**-0.5)
        self.w_mix = jax.random.normal(
            k2, (hidden_size, hidden_size), jnp.float32
        ) * (hidden_size**-0.5)

        @jax.jit
        def encode(img):  # [H, W, 3] float32 in [0, 1]
            H, W, _ = img.shape
            p = self.patch
            patches = (
                img[: H - H % p, : W - W % p]
                .reshape(H // p, p, W // p, p, 3)
                .transpose(0, 2, 1, 3, 4)
                .reshape(-1, p * p * 3)
            )
            x = patches @ self.w_proj
            return x + jnp.tanh(x) @ self.w_mix  # [n_patches, hidden]

        self._encode = encode

    def __call__(self, image: np.ndarray) -> np.ndarray:
        return np.asarray(self._encode(image.astype(np.float32)))


def decode_image(request: dict) -> np.ndarray:
    """Accept {"pixels": [[...]] } (nested lists) or {"image_b64",
    "shape"} (raw float32 bytes) — no PIL dependency needed."""
    if "pixels" in request:
        return np.asarray(request["pixels"], np.float32)
    raw = base64.b64decode(request["image_b64"])
    return np.frombuffer(raw, np.float32).reshape(request["shape"])


@service(dynamo={"namespace": "multimodal"}, resources={"tpu": 1})
class EncodeWorker:
    hidden_size: int = 2048
    patch: int = 16

    def __init__(self):
        self.encoder = None
        self.encoded = 0

    @async_on_start
    async def build(self) -> None:
        self.encoder = PatchEncoder(self.hidden_size, self.patch)

    @endpoint()
    async def encode(self, request: dict):
        image = decode_image(request)
        features = self.encoder(image)
        self.encoded += 1
        yield {
            "image_features": features.tolist(),
            "n_patches": int(features.shape[0]),
        }
