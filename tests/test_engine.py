"""TPU engine tests on the virtual CPU mesh: correctness of continuous
batching, prefix reuse, stop conditions, cancellation, KV events."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.engine import EngineConfig, KvPageManager, TPUEngine
from dynamo_exp_tpu.models import TINY, forward, init_kv_cache, init_params
from dynamo_exp_tpu.protocols.common import BackendInput, FinishReason


PS = 8


def tiny_engine(**kw) -> TPUEngine:
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=4,
        page_size=PS,
        num_pages=64,
        max_model_len=128,
        eos_token_ids=[2],
        **kw,
    )
    from dynamo_exp_tpu.parallel import single_device_mesh

    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def collect(engine, binput):
    stream = await engine.generate(binput.to_dict())
    tokens, final = [], None
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
        if item.get("finish_reason"):
            final = item
    return tokens, final


def greedy_oracle(prompt, n_steps):
    """Reference decode loop straight through the model forward."""
    cfg = TINY
    params = init_params(jax.random.PRNGKey(0), cfg)
    pmax = 16
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS)
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    toks = list(prompt)
    logits, k, v = forward(
        params, cfg,
        jnp.array([toks], jnp.int32),
        jnp.arange(len(toks), dtype=jnp.int32)[None, :],
        table, k, v,
    )
    out = []
    cur = int(np.asarray(logits)[0, len(toks) - 1].argmax())
    out.append(cur)
    for _ in range(n_steps - 1):
        pos = len(toks) + len(out) - 1
        logits, k, v = forward(
            params, cfg,
            jnp.array([[cur]], jnp.int32),
            jnp.array([[pos]], jnp.int32),
            table, k, v,
        )
        cur = int(np.asarray(logits)[0, 0].argmax())
        out.append(cur)
    return out


@pytest.fixture(scope="module")
def engine():
    eng = tiny_engine()
    eng.start()
    yield eng
    eng.stop()


async def test_greedy_decode_matches_oracle(engine):
    prompt = [5, 9, 17, 3, 11, 21, 8]
    want = greedy_oracle(prompt, 8)
    binput = BackendInput(token_ids=prompt)
    binput.stop_conditions.max_tokens = 8
    binput.stop_conditions.ignore_eos = True
    tokens, final = await collect(engine, binput)
    assert tokens == want
    assert final["finish_reason"] == "length"
    assert final["prompt_tokens"] == len(prompt)
    assert final["completion_tokens"] == 8


@pytest.mark.slow  # 6 concurrent streams + oracle replays: minutes of
# row-bucket compiles on a small CPU box; still in make test/nightly.
async def test_concurrent_requests_batch(engine):
    async def one(seed):
        prompt = list(np.random.RandomState(seed).randint(3, 200, size=12))
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 12
        b.stop_conditions.ignore_eos = True
        return prompt, await collect(engine, b)

    results = await asyncio.gather(*[one(s) for s in range(6)])
    for prompt, (tokens, final) in results:
        assert len(tokens) == 12
        assert final["finish_reason"] == "length"
        # Batched decode must equal the single-request oracle.
        assert tokens == greedy_oracle(prompt, 12)


async def test_prefix_reuse_hits_cache(engine):
    prompt = list(np.random.RandomState(42).randint(3, 200, size=3 * PS + 2))
    b = BackendInput(token_ids=prompt)
    b.stop_conditions.max_tokens = 4
    b.stop_conditions.ignore_eos = True
    first, _ = await collect(engine, b)
    hits_before = engine.kv.hits
    second, _ = await collect(engine, b)
    assert second == first  # identical result through the cached prefix
    assert engine.kv.hits > hits_before  # and it actually reused pages


async def test_max_tokens_and_eos(engine):
    prompt = [4, 4, 4, 4]
    b = BackendInput(token_ids=prompt)
    b.stop_conditions.max_tokens = 3
    b.stop_conditions.ignore_eos = True
    tokens, final = await collect(engine, b)
    assert len(tokens) == 3
    assert final["finish_reason"] == "length"


async def test_cancellation_mid_stream(engine):
    from dynamo_exp_tpu.runtime.engine import AsyncEngineContext

    prompt = [7, 8, 9, 10, 11]
    b = BackendInput(token_ids=prompt)
    b.stop_conditions.max_tokens = 10_000
    b.stop_conditions.ignore_eos = True
    ctx = AsyncEngineContext()
    stream = await engine.generate(b.to_dict(), ctx)
    seen = 0
    async for item in stream:
        seen += len(item.get("token_ids", []))
        if seen >= 3:
            ctx.stop_generating()
        if item.get("finish_reason"):
            assert item["finish_reason"] == "cancelled"
            break
    assert seen < 200  # stopped long before max_tokens


async def test_sequence_longer_than_capacity_rejected(engine):
    b = BackendInput(token_ids=list(range(1, 200)))  # > max_model_len=128
    tokens, final = await collect(engine, b)
    assert tokens == []
    assert final["finish_reason"] == "error"


async def test_waiting_queue_reaps_cancelled_anywhere(engine):
    """Satellite regression: a request cancelled while queued BEHIND
    other waiting work is reaped from the middle of the deque (emitting
    its CANCELLED finish) instead of inflating queue gauges until it
    reaches the head."""
    from dynamo_exp_tpu.runtime.engine import AsyncEngineContext

    async def one(ctx=None, max_tokens=6):
        prompt = list(np.random.RandomState(max_tokens).randint(3, 200, size=8))
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = max_tokens
        b.stop_conditions.ignore_eos = True
        stream = await engine.generate(b.to_dict(), ctx)
        tokens, final = [], None
        async for item in stream:
            tokens.extend(item.get("token_ids", []))
            if item.get("finish_reason"):
                final = item
        return tokens, final

    # Fill all 4 slots with long-running work, then queue two more; the
    # one cancelled while waiting must finish CANCELLED without tokens.
    busy = [asyncio.create_task(one(max_tokens=48 + i)) for i in range(4)]
    victim_ctx = AsyncEngineContext()
    queued_victim = asyncio.create_task(one(victim_ctx, max_tokens=8))
    queued_tail = asyncio.create_task(one(max_tokens=9))
    while engine.metrics()["num_requests_waiting"] < 2:
        await asyncio.sleep(0.01)
    victim_ctx.stop_generating()  # cancel while queued mid-deque
    tokens, final = await queued_victim
    assert tokens == []
    assert final["finish_reason"] == "cancelled"
    # Everything else completes normally.
    for t in busy:
        _, f = await t
        assert f["finish_reason"] == "length"
    _, f = await queued_tail
    assert f["finish_reason"] == "length"


def test_kv_events_emitted():
    events = []
    cfg = EngineConfig(
        model=TINY, max_decode_slots=2, page_size=PS, num_pages=32,
        max_model_len=64, eos_token_ids=[],
    )
    from dynamo_exp_tpu.parallel import single_device_mesh

    eng = TPUEngine(cfg, mesh=single_device_mesh(), kv_event_cb=events.append)
    eng.start()
    try:
        prompt = list(np.random.RandomState(1).randint(3, 200, size=2 * PS + 1))
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = PS + 2  # crosses one more boundary
        b.stop_conditions.ignore_eos = True
        asyncio.run(collect(eng, b))
    finally:
        eng.stop()
    stored = [e for e in events if e.kind == "stored"]
    # 2 full prompt pages + at least one page completed during decode.
    assert len(stored) >= 3
    # Chained: each stored event carries its parent hash.
    assert stored[1].parent_hash == stored[0].seq_hashes[0]


def test_kv_manager_lru_eviction():
    events = []
    kv = KvPageManager(num_pages=4, page_size=4, event_cb=events.append)
    a = kv.allocate_sequence([1, 2, 3, 4, 5], max_pages=8)  # 2 pages
    assert a is not None
    assert a.cached_len == 0
    assert a.uploads == []
    kv.register_full_page(a.page_ids[0], seq_hash=111, tokens=[1, 2, 3, 4])
    kv.release_sequence(a.page_ids)
    # Page with hash 111 is parked; matching prompt revives it.
    b = kv.allocate_sequence([1, 2, 3, 4, 9], max_pages=8)
    assert b is not None
    assert b.cached_len in (0, 4)
    # Exhaust the pool so the parked page gets evicted.
    kv.release_sequence(b.page_ids)
    c = kv.allocate_sequence(list(range(100, 116)), max_pages=8)  # 4 pages
    assert c is not None
    removed = [e for e in events if e.kind == "removed"]
    assert any(111 in e.seq_hashes for e in removed)


def test_kv_manager_matched_parked_pages_not_double_counted():
    """Regression: a prompt that both matches a parked page and needs
    every remaining page must be deferred, not crash the allocator.

    num_pages=4, ps=4: one registered parked page + 3 free. A 17-token
    prompt matching that page needs 5 pages total -> must return None
    (4 takeable pages would have been miscounted as satisfying
    need_fresh=4 while the match also consumes the parked one)."""
    from dynamo_exp_tpu.tokens import compute_block_hashes_for_seq

    kv = KvPageManager(num_pages=4, page_size=4)
    a = kv.allocate_sequence([1, 2, 3, 4, 5], max_pages=8)
    h = compute_block_hashes_for_seq([1, 2, 3, 4], 4)[0]
    kv.register_full_page(a.page_ids[0], seq_hash=h, tokens=[1, 2, 3, 4])
    kv.release_sequence(a.page_ids)
    assert kv.allocate_sequence([1, 2, 3, 4] + list(range(10, 23)), max_pages=8) is None


def test_make_engine_registry_jax():
    """The factory's jax branch must construct a working engine
    (round-1 regression: it referenced a nonexistent class/method)."""
    from dynamo_exp_tpu.engines import make_engine

    eng = make_engine(
        "jax",
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=32,
        max_model_len=64,
        seed=0,
    )
    assert isinstance(eng, TPUEngine)
    assert eng.cfg.max_decode_slots == 2

    async def roundtrip():
        b = BackendInput(token_ids=[5, 6, 7])
        b.stop_conditions.max_tokens = 4
        b.stop_conditions.ignore_eos = True
        toks, final = await collect(eng, b)
        assert len(toks) == 4
        assert final["finish_reason"] == "length"

    try:
        asyncio.run(roundtrip())
    finally:
        eng.stop()


def test_make_engine_registry_echo():
    from dynamo_exp_tpu.engines import EchoEngineCore, make_engine

    assert isinstance(make_engine("echo_core"), EchoEngineCore)
