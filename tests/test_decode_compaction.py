"""Occupancy-proportional decode: batch compaction, on-device stop,
chained windows, and batched KV page movement (docs/engine_perf.md).

CPU proofs of the acceptance criteria: the compiled decode variant at
occupancy 1 has batch dim 1 (not max_decode_slots), greedy streams are
byte-identical to the uncompacted semantics (mid-window EOS, page-pool
dry stalls, disagg remote inject, chained on/off), a ~190-page disagg
extract/inject round-trip is O(1) dispatches per sequence, and a timed
micro-bench shows the rows-1 window beating the rows-8 window.
"""

import asyncio
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.engine.scheduler import RemoteKv
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.models.config import ModelConfig
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput

from .test_engine import greedy_oracle

PS = 8


def make_engine(**kw) -> TPUEngine:
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=kw.pop("max_decode_slots", 8),
        page_size=PS,
        num_pages=kw.pop("num_pages", 64),
        max_model_len=kw.pop("max_model_len", 128),
        eos_token_ids=kw.pop("eos_token_ids", []),
        **kw,
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def collect(engine, prompt, max_tokens, **opts):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = opts.pop("ignore_eos", True)
    for key, val in opts.items():
        setattr(b.sampling_options, key, val)
    stream = await engine.generate(b.to_dict())
    tokens, final = [], None
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
        if item.get("finish_reason"):
            final = item
    return tokens, final


# ------------------------------------------------------ compaction variants
def test_occupancy_one_compiles_rows_one_variant():
    """One active sequence of 8 slots must run the rows=1 decode window,
    not the full-B one (decode cost proportional to occupancy)."""
    eng = make_engine(max_decode_slots=8)
    eng.start()
    try:
        prompt = [5, 9, 17, 3, 11]
        tokens, _ = asyncio.run(collect(eng, prompt, 8))
        assert tokens == greedy_oracle(prompt, 8)
        rows_used = {key[0] for key in eng._ragged_fns if key[2]}
        assert rows_used == {1}
        m = eng.metrics()
        assert m["compiled_ragged_variants"] == len(eng._ragged_fns)

        # Saturating the slots compiles (and uses) a wider bucket.
        async def many():
            return await asyncio.gather(
                *[collect(eng, [3 + s, 7, 11, 13], 8) for s in range(8)]
            )

        asyncio.run(many())
        assert max(key[0] for key in eng._ragged_fns if key[2]) > 1
    finally:
        eng.stop()


def test_greedy_partition_unpolluted_by_sampler_row():
    """A creative (sampled) request must not drag greedy rows through
    the full-sampler window: the greedy rows keep their own variant and
    their streams stay byte-identical to the all-greedy run."""
    eng = make_engine(max_decode_slots=4)
    eng.start()
    try:
        prompts = [
            list(np.random.RandomState(s).randint(3, 200, size=10))
            for s in range(3)
        ]

        async def mixed():
            greedy = [collect(eng, p, 10) for p in prompts]
            creative = collect(
                eng, [9, 9, 9, 9], 10, temperature=0.9, top_p=0.9
            )
            return await asyncio.gather(*greedy, creative)

        results = asyncio.run(mixed())
        for prompt, (tokens, _) in zip(prompts, results[:3]):
            assert tokens == greedy_oracle(prompt, 10)
        # Both partitions compiled: greedy variants + a sampler variant.
        samplers = {key[3] for key in eng._ragged_fns}
        assert samplers == {False, True}
    finally:
        eng.stop()


# -------------------------------------------------------- on-device stopping
def test_mid_window_eos_stream_identical():
    """EOS hit mid-window: the on-device stop parks the row, and the
    emitted stream is byte-identical to the reference decode up to (and
    including) the EOS token."""
    probe = make_engine(decode_window=4)
    probe.start()
    try:
        prompt = [5, 9, 17, 3, 11, 21, 8]
        free_run, _ = asyncio.run(collect(probe, prompt, 12))
    finally:
        probe.stop()
    # Pick the token at index 1: decode windows cover indices 1-4, 5-8,
    # ..., so stopping there is a mid-window stop (3 overshoot steps the
    # device parks instead of writing).
    eos = free_run[1]
    assert free_run[0] != eos  # stops at its first occurrence
    stop_at = free_run.index(eos) + 1

    eng = make_engine(decode_window=4, eos_token_ids=[eos])
    eng.start()
    try:
        tokens, final = asyncio.run(
            collect(eng, prompt, 12, ignore_eos=False)
        )
        assert tokens == free_run[:stop_at]
        assert final["finish_reason"] == "eos"
        # The overshoot the host discarded is visible in the counter.
        assert eng.metrics()["decode_wasted_steps"] >= 0
    finally:
        eng.stop()


def test_min_tokens_gates_device_stop():
    """An EOS sampled before min_tokens must be kept and generation must
    continue — the device gate mirrors check_stop's min_tokens rule."""
    probe = make_engine(decode_window=4)
    probe.start()
    try:
        prompt = [5, 9, 17, 3, 11, 21, 8]
        free_run, _ = asyncio.run(collect(probe, prompt, 12))
    finally:
        probe.stop()
    eos = free_run[1]  # would stop at index 1 without the gate

    eng = make_engine(decode_window=4, eos_token_ids=[eos])
    eng.start()
    try:
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 12
        b.stop_conditions.min_tokens = 5
        b.stop_conditions.ignore_eos = False

        async def run():
            stream = await eng.generate(b.to_dict())
            toks, final = [], None
            async for item in stream:
                toks.extend(item.get("token_ids", []))
                if item.get("finish_reason"):
                    final = item
            return toks, final

        tokens, final = asyncio.run(run())
        # Generation ran past the early EOS; it stops at the first EOS
        # occurrence at index >= min_tokens (or runs to max_tokens).
        assert len(tokens) >= 5
        assert tokens == free_run[: len(tokens)]
        if final["finish_reason"] == "eos":
            assert tokens[-1] == eos
    finally:
        eng.stop()


@pytest.mark.slow  # stall + resume crosses many row-bucket compile
# variants; the oracle run doubles it. Still in make test/nightly.
def test_pool_dry_stall_equivalence():
    """A sequence stalled by a dry page pool mid-decode must resume and
    produce the same greedy stream once pages free up."""
    # 12 pages: A (3-page prompt + 1 decode page) and B (3-page prompt +
    # 5 decode pages) oversubscribe the pool, so B stalls until A
    # finishes and releases.
    eng = make_engine(max_decode_slots=2, num_pages=12)
    eng.start()
    try:
        rs = np.random.RandomState(7)
        prompt_a = list(rs.randint(3, 200, size=3 * PS))
        prompt_b = list(rs.randint(3, 200, size=3 * PS))

        async def both():
            return await asyncio.gather(
                collect(eng, prompt_a, 8),
                collect(eng, prompt_b, 40),
            )

        (toks_a, fin_a), (toks_b, fin_b) = asyncio.run(both())
        assert toks_a == greedy_oracle(prompt_a, 8)
        assert toks_b == greedy_oracle(prompt_b, 40)
        assert fin_a["finish_reason"] == "length"
        assert fin_b["finish_reason"] == "length"
    finally:
        eng.stop()


def test_chained_vs_unchained_streams_identical():
    """The chained (window-N+1-in-flight) dispatch path must be
    invisible in the token stream."""
    outs = {}
    for chained in (True, False):
        eng = make_engine(max_decode_slots=2, chained_decode=chained)
        eng.start()
        try:
            rs = np.random.RandomState(3)
            prompts = [list(rs.randint(3, 200, size=9)) for _ in range(2)]

            async def both(e=eng, ps=prompts):
                return await asyncio.gather(
                    *[collect(e, p, 40) for p in ps]
                )

            outs[chained] = asyncio.run(both())
        finally:
            eng.stop()
    assert [t for t, _ in outs[True]] == [t for t, _ in outs[False]]
    for tokens, _ in outs[True]:
        assert len(tokens) == 40


def test_late_arrival_joins_chained_decode():
    """A request admitted while a chained decode window is in flight
    must join the batch promptly — the chain must break for it instead
    of starving it behind the established rows (regression: _can_chain
    only checked PREFILL slots, so a row promoted to ACTIVE mid-chain
    was never re-included)."""
    eng = make_engine(max_decode_slots=4)
    eng.start()
    try:

        async def run():
            rs = np.random.RandomState(5)
            long_jobs = [
                asyncio.create_task(
                    collect(eng, list(rs.randint(3, 200, size=9)), 64)
                )
                for _ in range(2)
            ]
            # Let the long pair establish a steady chained cadence:
            # wait until windows are demonstrably stepping (a fixed
            # sleep is load-sensitive under a busy suite).
            steps0 = eng.steps
            while eng.steps < steps0 + 2 * eng.cfg.decode_window:
                await asyncio.sleep(0.01)
            order: list[str] = []

            async def tagged(tag, coro):
                out = await coro
                order.append(tag)
                return out

            late = asyncio.create_task(
                tagged("late", collect(eng, [7, 8, 9, 10], 6))
            )
            for i, j in enumerate(long_jobs):
                long_jobs[i] = asyncio.create_task(tagged("long", j))
            await asyncio.gather(late, *long_jobs)
            return order

        order = asyncio.run(run())
        # The 6-token latecomer must not be serialized behind the
        # 64-token pair.
        assert order[0] == "late", order
    finally:
        eng.stop()


# ------------------------------------------------- batched KV page movement
def test_disagg_roundtrip_190_pages_single_dispatch():
    """A ~190-page prompt extracts with ONE gather dispatch + ONE host
    sync, injects with ONE scatter dispatch, matches the per-page gather
    bit-for-bit, and the injected decode equals the local decode."""
    mcfg = ModelConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position_embeddings=2048,
        rms_norm_eps=1e-5,
    )

    def engine():
        cfg = EngineConfig(
            model=mcfg,
            max_decode_slots=2,
            page_size=PS,
            num_pages=256,
            max_model_len=1600,
            eos_token_ids=[],
            kv_dtype="float32",  # bit-exact host bounce
        )
        return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)

    prompt = list(np.random.RandomState(0).randint(3, 250, size=189 * PS + 3))
    n_pages = (len(prompt) + PS - 1) // PS
    assert n_pages == 190

    eng_a = engine()
    # Spy on the extraction to learn which device pages held the prompt
    # (they are released when the extract sequence finishes).
    captured: dict = {}
    orig_extract = eng_a._extract_prompt_pages

    def spy(seq):
        captured["pids"] = list(seq.page_ids[:n_pages])
        return orig_extract(seq)

    eng_a._extract_prompt_pages = spy
    eng_a.start()
    try:
        first_tok, pages, _lease = asyncio.run(
            eng_a.prefill_extract(BackendInput(token_ids=prompt).to_dict())
        )
        assert len(pages) == n_pages
        assert eng_a.kv_move_dispatches == 1  # O(1), not one per page
        assert eng_a.kv_page_moves == n_pages

        # Identical to the per-page path (released pages keep their
        # content until reallocated; nothing else has run yet).
        per_page = jax.jit(lambda k, v, pid: (k[:, pid], v[:, pid]))
        for probe in (0, 17, n_pages - 1):
            pid = captured["pids"][probe]
            k_pg, v_pg = per_page(eng_a.k_cache, eng_a.v_cache, pid)
            np.testing.assert_array_equal(pages[probe][0], np.asarray(k_pg))
            np.testing.assert_array_equal(pages[probe][1], np.asarray(v_pg))

        # Local reference decode on the prefill engine (prefix-cached).
        local, _ = asyncio.run(collect(eng_a, prompt, 6))
    finally:
        eng_a.stop()

    eng_b = engine()
    eng_b.start()
    try:
        b = BackendInput(token_ids=prompt)
        b.stop_conditions.max_tokens = 6
        b.stop_conditions.ignore_eos = True

        async def injected():
            stream = await eng_b.generate(
                b.to_dict(),
                remote_kv=RemoteKv(first_token=first_tok, pages=pages),
            )
            toks = []
            async for item in stream:
                toks.extend(item.get("token_ids", []))
            return toks

        toks_b = asyncio.run(injected())
        assert eng_b.kv_move_dispatches == 1  # one batched inject
        assert eng_b.kv_page_moves == n_pages
        assert toks_b == local
    finally:
        eng_b.stop()


# ------------------------------------------------------------ recompile guard
def test_recompile_guard_steady_state():
    """After warmup over the workload's occupancy/sampler envelope, a
    steady-state mixed workload must not grow the compiled-variant
    caches (silent recompiles masquerade as slow serving)."""
    eng = make_engine(max_decode_slots=4)
    eng.start()
    try:
        rs = np.random.RandomState(11)

        def prompt():
            return list(rs.randint(3, 200, size=10))

        async def run_mix(n_greedy, n_sampled):
            jobs = [collect(eng, prompt(), 8) for _ in range(n_greedy)]
            jobs += [
                collect(eng, prompt(), 8, temperature=0.8)
                for _ in range(n_sampled)
            ]
            return await asyncio.gather(*jobs)

        # Warmup: cover every row bucket either partition can shrink
        # through as requests drain (1/2/4), both samplers. Whether N
        # concurrent submissions share one admit pass (one rows-N
        # prefill batch) or split across loop iterations is an
        # OS-scheduling race, so one round per shape can miss a bucket —
        # repeat the envelope until the variant caches stop growing.
        for n in (1, 2, 4):
            asyncio.run(run_mix(n, 0))
            asyncio.run(run_mix(0, n))
        asyncio.run(run_mix(2, 2))
        for _ in range(5):
            before = len(eng._ragged_fns)
            asyncio.run(run_mix(4, 0))
            asyncio.run(run_mix(0, 4))
            asyncio.run(run_mix(2, 2))
            if len(eng._ragged_fns) == before:
                break
        variants = len(eng._ragged_fns)

        for _ in range(3):
            asyncio.run(run_mix(2, 2))
        assert len(eng._ragged_fns) == variants
    finally:
        eng.stop()


# ------------------------------------------------------- proportionality time
def test_single_sequence_decode_faster_than_full_batch():
    """CPU proof of occupancy proportionality, in the regime the
    compaction targets (long-context decode, where per-row KV
    gather/attention traffic dominates — the Ragged Paged Attention
    premise): the rows=1 compiled window must beat the fixed-B
    (rows=max_decode_slots) window in wall time, and its compiled FLOP
    count must be proportionally smaller regardless of backend.

    (At toy model sizes the fixed-B window is weight-bandwidth-bound
    and XLA:CPU lowers batch-1 matrix-vector dots through a slow loop
    fusion, so short-context wall time is NOT a faithful proxy — the
    1024-token context below is, with a ~5x measured margin.)"""
    mcfg = ModelConfig(
        vocab_size=4096,
        hidden_size=256,
        intermediate_size=512,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position_embeddings=2048,
        rms_norm_eps=1e-5,
        dtype="float32",
    )
    cfg = EngineConfig(
        model=mcfg,
        max_decode_slots=32,
        page_size=32,
        num_pages=64,
        max_model_len=1024,
        eos_token_ids=[],
        kv_dtype="float32",
    )
    eng = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    k, v = eng.k_cache, eng.v_cache
    S = cfg.device_stop_width
    K = cfg.decode_window
    pages = cfg.max_pages_per_seq  # 32 pages x 32 tokens: 1k context

    def window_args(rows):
        return (
            jnp.zeros(rows, jnp.int32),  # tokens
            jnp.full(rows, 1000, jnp.int32),  # positions: deep context
            jnp.full(rows, cfg.max_model_len - 1, jnp.int32),
            jnp.tile(jnp.arange(pages, dtype=jnp.int32)[None], (rows, 1)),
            jnp.full((rows, S), -1, jnp.int32),  # stop set
            jnp.zeros(rows, jnp.int32),  # eos gate
            jnp.full(rows, K, jnp.int32),  # budget gate: never
        )

    def timed(rows, k, v, reps=5):
        fn = eng._ragged_fn(rows, pages, True, False, False)
        args = window_args(rows)
        times = []
        for _ in range(reps + 1):  # first call compiles; drop it
            t0 = time.perf_counter()
            ys, k, v, _, _ = fn(eng.params, k, v, *args)
            jax.block_until_ready(ys)
            times.append(time.perf_counter() - t0)
        return sorted(times[1:])[reps // 2], k, v

    # Backend-independent proportionality: the compiled rows=1 program
    # does a fraction of the fixed-B program's FLOPs.
    def flops(rows):
        fn = eng._ragged_fn(rows, pages, True, False, False)
        ca = fn.lower(eng.params, k, v, *window_args(rows)).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        return float(ca["flops"])

    assert flops(1) * 8 < flops(cfg.max_decode_slots)

    t1, k, v = timed(1, k, v)
    t_full, k, v = timed(cfg.max_decode_slots, k, v)
    assert t1 * 1.5 < t_full, (
        f"rows=1 window ({t1:.4f}s) not measurably faster than fixed-B "
        f"rows={cfg.max_decode_slots} ({t_full:.4f}s)"
    )


# ------------------------------------------------------------- drain-on-stop
def test_stop_drains_copy_stream():
    """stop() must flush + drain queued host-tier offloads instead of
    discarding them (a graceful drain keeps its G2 pages)."""
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=8,
        max_model_len=128,
        eos_token_ids=[],
        host_cache_pages=32,
        kv_dtype="float32",
    )
    eng = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    eng.start()
    rs = np.random.RandomState(0)
    # A parks 3 registered pages; B's allocation evicts them into the
    # offload queue.
    asyncio.run(collect(eng, list(rs.randint(3, 200, size=3 * PS + 2)), 6))
    asyncio.run(collect(eng, list(rs.randint(3, 200, size=5 * PS + 2)), 6))
    eng.stop()  # no explicit drain: stop() itself must commit the queue
    assert eng.copy_stream is None
    assert eng.host_pool.stores > 0
