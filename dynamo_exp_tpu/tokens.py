"""Token sequences, fixed-size token blocks, and chained block hashing.

This is the foundation shared by the KV-aware router (prefix matching over
block hashes) and the KV block manager (content-addressed block reuse).

Capability parity with the reference's token/block layer
(``/root/reference/lib/tokens/src/lib.rs:44-369`` and
``lib/llm/src/tokens.rs``): fixed-size blocks of token ids, a per-block
*local* hash over the block's tokens, and a *sequence hash* chaining each
block to its prefix so equal sequence hashes imply equal full prefixes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence

from . import native as _native

# Version tag of the block-hash algorithm. Bumped whenever the hash
# function changes (v2 = the splitmix64 chain that replaced xxh3_64);
# mixed into the default seed so peers running different algorithm
# versions live in disjoint hash spaces *by construction*, and carried
# on the KV-event wire (``kv_router.protocols.RouterEvent``) so a
# mixed-version deployment logs a visible warning instead of silently
# losing prefix reuse until the rollout completes.
HASH_ALGO_VERSION = 2

# Salt seeds the first block's chain so that hashes from different
# deployments/configurations don't collide by construction.
DEFAULT_HASH_SEED = 1337 ^ (HASH_ALGO_VERSION << 32)


def compute_block_hash(tokens: Sequence[int], seed: int = DEFAULT_HASH_SEED) -> int:
    """Hash one block's tokens (local hash, not chained). Dispatches to
    the C++ extension (``native/blockhash.cpp``) with a bit-exact Python
    fallback."""
    return _native.block_hash(tokens, seed)


def chain_hash(parent: int | None, local: int, seed: int = DEFAULT_HASH_SEED) -> int:
    """Chain a block's local hash onto its prefix's sequence hash."""
    return _native.chain_hash(parent, local, seed)


def compute_block_hashes_for_seq(
    tokens: Sequence[int], block_size: int, seed: int = DEFAULT_HASH_SEED
) -> list[int]:
    """Sequence hashes for every *complete* block of ``tokens``.

    This is what the router hashes incoming requests with (reference:
    ``lib/llm/src/kv_router/indexer.rs:123`` ``compute_block_hash_for_seq``)
    — one native call over the whole prompt, not a Python loop per block.
    """
    return _native.seq_hashes(tokens, block_size, seed)


@dataclass(frozen=True)
class TokenBlock:
    """An immutable, complete block of ``block_size`` tokens.

    ``sequence_hash`` identifies the full token prefix ending at this block;
    ``block_hash`` is the local (unchained) hash of just this block.
    """

    tokens: tuple[int, ...]
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: int | None

    @property
    def block_size(self) -> int:
        return len(self.tokens)


@dataclass
class PartialTokenBlock:
    """The mutable tail block currently being filled."""

    block_size: int
    seed: int = DEFAULT_HASH_SEED
    tokens: list[int] = field(default_factory=list)
    parent_sequence_hash: int | None = None

    @property
    def remaining(self) -> int:
        return self.block_size - len(self.tokens)

    def push(self, token: int) -> TokenBlock | None:
        """Append one token; returns the completed block when full."""
        self.tokens.append(int(token))
        if len(self.tokens) < self.block_size:
            return None
        local = compute_block_hash(self.tokens, self.seed)
        seq = chain_hash(self.parent_sequence_hash, local, self.seed)
        block = TokenBlock(
            tokens=tuple(self.tokens),
            block_hash=local,
            sequence_hash=seq,
            parent_sequence_hash=self.parent_sequence_hash,
        )
        self.tokens = []
        self.parent_sequence_hash = seq
        return block


class TokenBlockSequence:
    """A growing token sequence chunked into hash-chained blocks.

    Mirrors the capability of the reference's ``TokenBlockSequence``
    (``lib/tokens/src/lib.rs:277-369``): push tokens one at a time, get a
    callback/event whenever a block completes (used by the engine's cache
    manager to emit KV "stored" events), and expose all completed blocks.
    """

    def __init__(
        self,
        tokens: Iterable[int] = (),
        block_size: int = 64,
        seed: int = DEFAULT_HASH_SEED,
        on_block: Callable[[TokenBlock], None] | None = None,
    ):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.seed = seed
        self._on_block = on_block
        self._blocks: list[TokenBlock] = []
        self._partial = PartialTokenBlock(block_size=block_size, seed=seed)
        self._count = 0
        self.extend(tokens)

    @property
    def blocks(self) -> list[TokenBlock]:
        return self._blocks

    @property
    def partial_tokens(self) -> list[int]:
        return self._partial.tokens

    def __len__(self) -> int:
        return self._count

    def all_tokens(self) -> list[int]:
        out: list[int] = []
        for b in self._blocks:
            out.extend(b.tokens)
        out.extend(self._partial.tokens)
        return out

    def block_hashes(self) -> list[int]:
        """Chained sequence hashes of all completed blocks."""
        return [b.sequence_hash for b in self._blocks]

    def push(self, token: int) -> TokenBlock | None:
        self._count += 1
        block = self._partial.push(token)
        if block is not None:
            self._blocks.append(block)
            if self._on_block is not None:
                self._on_block(block)
        return block

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        completed = []
        for t in tokens:
            b = self.push(t)
            if b is not None:
                completed.append(b)
        return completed

    def truncate(self, num_tokens: int) -> None:
        """Truncate the sequence to ``num_tokens`` (e.g. on preemption).

        Does NOT re-fire ``on_block`` for blocks that remain complete — the
        cache manager already recorded them; replaying "stored" events would
        corrupt the router's index.
        """
        if num_tokens > self._count:
            raise ValueError(f"cannot truncate {self._count} tokens to {num_tokens}")
        # Surviving complete blocks are unchanged by construction; only the
        # partial tail needs rebuilding (no re-hashing of the kept prefix).
        keep_blocks = num_tokens // self.block_size
        tail = self.all_tokens()[keep_blocks * self.block_size : num_tokens]
        self._blocks = self._blocks[:keep_blocks]
        self._partial = PartialTokenBlock(
            block_size=self.block_size,
            seed=self.seed,
            parent_sequence_hash=(
                self._blocks[-1].sequence_hash if self._blocks else None
            ),
        )
        self._count = keep_blocks * self.block_size
        on_block, self._on_block = self._on_block, None
        try:
            self.extend(tail)
        finally:
            self._on_block = on_block
