"""Model-layer correctness: paged incremental forward == dense oracle,
TP-sharded forward == single-device forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_exp_tpu.models import (
    TINY,
    forward,
    init_kv_cache,
    init_params,
    kv_cache_shardings,
    param_shardings,
)
from dynamo_exp_tpu.ops import dense_causal_attention, paged_attention, write_kv_pages
from dynamo_exp_tpu.parallel import build_mesh, shard_pytree


PS = 8  # page size


def _full_forward_logits(params, cfg, token_list):
    """Oracle: run the whole sequence in one prefill pass, fresh cache."""
    T = len(token_list)
    pmax = (T + PS - 1) // PS
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
    tokens = jnp.array([token_list], dtype=jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1  # pages 1..pmax
    logits, _, _ = forward(params, cfg, tokens, positions, table, k, v)
    return np.asarray(logits[0])


def test_paged_attention_matches_dense():
    key = jax.random.PRNGKey(0)
    B, T, H, Hkv, D = 2, 16, 4, 2, 8
    q = jax.random.normal(key, (B, T, H, D), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, T, Hkv, D), jnp.float32)

    want = dense_causal_attention(q, k, v)

    # Put k/v into pages: each batch row owns its own pages. Pools hold
    # (Hkv, D) collapsed into the lane dim.
    pmax = T // PS
    kc = jnp.zeros((B * pmax + 1, PS, Hkv * D))
    vc = jnp.zeros_like(kc)
    table = (jnp.arange(B * pmax, dtype=jnp.int32).reshape(B, pmax)) + 1
    pos = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (B, 1))
    flat_pos = pos.reshape(-1)
    bidx = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)
    page_ids = table[bidx, flat_pos // PS]
    kc, vc = write_kv_pages(
        kc, vc,
        k.reshape(B * T, Hkv * D), v.reshape(B * T, Hkv * D),
        page_ids, flat_pos % PS, jnp.ones(B * T, bool),
    )
    got = paged_attention(q, kc, vc, table, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_incremental_decode_matches_full_prefill():
    cfg = TINY
    params = jax.tree.map(
        lambda x: x.astype(jnp.float32), init_params(jax.random.PRNGKey(7), cfg)
    )
    toks = list(np.random.RandomState(0).randint(1, cfg.vocab_size, size=21))

    want = _full_forward_logits(params, cfg, toks)

    # Incremental: prefill first 13 tokens, then decode one at a time.
    pmax = 4
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS, dtype=jnp.float32)
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    split = 13
    tokens = jnp.array([toks[:split]], dtype=jnp.int32)
    positions = jnp.arange(split, dtype=jnp.int32)[None, :]
    logits, k, v = forward(params, cfg, tokens, positions, table, k, v)
    np.testing.assert_allclose(
        np.asarray(logits[0]), want[:split], rtol=1e-4, atol=1e-4
    )
    for i in range(split, len(toks)):
        tok = jnp.array([[toks[i]]], dtype=jnp.int32)
        pos = jnp.array([[i]], dtype=jnp.int32)
        logits, k, v = forward(params, cfg, tok, pos, table, k, v)
        np.testing.assert_allclose(
            np.asarray(logits[0, 0]), want[i], rtol=1e-4, atol=1e-4
        )


def test_padding_rows_do_not_corrupt_cache():
    """Inactive decode slots (position == -1) must not write KV anywhere."""
    cfg = TINY
    params = init_params(jax.random.PRNGKey(3), cfg)
    k, v = init_kv_cache(cfg, num_pages=8, page_size=PS)
    table = jnp.array([[1, 2], [3, 4]], dtype=jnp.int32)
    tokens = jnp.array([[5], [0]], dtype=jnp.int32)
    positions = jnp.array([[0], [-1]], dtype=jnp.int32)  # slot 1 inactive
    _, k2, v2 = forward(params, cfg, tokens, positions, table, k, v)
    # Slot 1's pages (3, 4) must be untouched.
    np.testing.assert_array_equal(np.asarray(k2[:, 3:5]), np.asarray(k[:, 3:5]))
    # Slot 0 wrote page 1 offset 0.
    assert np.abs(np.asarray(k2[:, 1, 0])).sum() > 0


def test_tp_sharded_forward_matches_single_device():
    cfg = TINY  # 2 kv heads -> tp=2
    params = init_params(jax.random.PRNGKey(11), cfg)
    toks = list(np.random.RandomState(1).randint(1, cfg.vocab_size, size=9))
    want = _full_forward_logits(
        jax.tree.map(lambda x: x.astype(jnp.float32), params), cfg, toks
    )

    mesh = build_mesh(tp=2)
    sp, _ = shard_pytree(mesh, params, param_shardings(cfg))
    T = len(toks)
    pmax = (T + PS - 1) // PS
    kspec, vspec = kv_cache_shardings()
    k, v = init_kv_cache(cfg, num_pages=pmax + 1, page_size=PS)
    from jax.sharding import NamedSharding

    k = jax.device_put(k, NamedSharding(mesh, kspec))
    v = jax.device_put(v, NamedSharding(mesh, vspec))
    tokens = jnp.array([toks], dtype=jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    table = jnp.arange(pmax, dtype=jnp.int32)[None, :] + 1
    fwd = jax.jit(forward, static_argnums=(1,))
    logits, _, _ = fwd(sp, cfg, tokens, positions, table, k, v)
    # bf16 params => loose tolerance; checking agreement not exactness.
    np.testing.assert_allclose(
        np.asarray(logits[0]), want, rtol=0.1, atol=0.15
    )


def test_sampling_greedy_and_topk():
    from dynamo_exp_tpu.ops import sample_tokens

    logits = jnp.array([[0.0, 5.0, 1.0, 2.0], [3.0, 0.0, 0.0, 0.0]], jnp.float32)
    out = sample_tokens(
        logits,
        jax.random.PRNGKey(0),
        temperature=jnp.zeros(2),
        top_k=jnp.zeros(2, jnp.int32),
        top_p=jnp.ones(2),
    )
    np.testing.assert_array_equal(np.asarray(out), [1, 0])
    # top_k=1 at any temperature is greedy.
    out = sample_tokens(
        logits,
        jax.random.PRNGKey(1),
        temperature=jnp.full(2, 0.9),
        top_k=jnp.ones(2, jnp.int32),
        top_p=jnp.ones(2),
    )
    np.testing.assert_array_equal(np.asarray(out), [1, 0])


def test_model_config_hashable_with_rope_scaling():
    from dynamo_exp_tpu.models import ModelConfig

    cfg = ModelConfig.from_hf_config(
        {"rope_scaling": {"rope_type": "llama3", "factor": 8.0}}
    )
    hash(cfg)  # must be usable as a jit static argument

    from dynamo_exp_tpu.ops import rope_frequencies

    base = rope_frequencies(8, 10000.0)
    lin = rope_frequencies(8, 10000.0, {"type": "linear", "factor": 4.0})
    np.testing.assert_allclose(np.asarray(lin), np.asarray(base) / 4.0)
    with pytest.raises(ValueError):
        rope_frequencies(8, 10000.0, {"type": "yarn", "factor": 2.0})


def test_top_p_zero_degrades_to_greedy():
    from dynamo_exp_tpu.ops import sample_tokens

    logits = jnp.array([[0.0, 5.0, 1.0, 2.0]], jnp.float32)
    out = sample_tokens(
        logits,
        jax.random.PRNGKey(0),
        temperature=jnp.full(1, 1.0),
        top_k=jnp.zeros(1, jnp.int32),
        top_p=jnp.zeros(1),
    )
    np.testing.assert_array_equal(np.asarray(out), [1])


def test_position_beyond_page_table_is_dropped_not_clamped():
    cfg = TINY
    params = init_params(jax.random.PRNGKey(5), cfg)
    k, v = init_kv_cache(cfg, num_pages=4, page_size=PS)
    table = jnp.array([[1, 2]], dtype=jnp.int32)  # capacity = 2 pages
    tokens = jnp.array([[7]], dtype=jnp.int32)
    positions = jnp.array([[2 * PS]], dtype=jnp.int32)  # one past capacity
    _, k2, _ = forward(params, cfg, tokens, positions, table, k, v)
    np.testing.assert_array_equal(np.asarray(k2), np.asarray(k))  # no write
