"""Ragged paged attention: one kernel for mixed prefill + decode.

This replaces ``ops/paged_decode.py`` (single-query-per-row decode) and
the chunked-prefill attention path with ONE kernel family that takes
per-row ``(query_len, kv_len, page table)`` — the Ragged Paged
Attention design (PAPERS.md, arXiv 2604.15464): chunked-prefill rows,
decode rows, and speculative-verify rows all ride the same dispatch.

Layout: queries arrive as a **flat token stream** ``q[N, H, D]`` with
per-token ``positions[N]`` (absolute context position, ``-1`` =
padding) and ``row_of[N]`` (which batch row owns the token). Rows own
KV pages via ``page_table[R, Pmax]``; a token at position ``p`` attends
causally to its row's kv positions ``<= p``. Total compute therefore
tracks the *true* total query tokens — a lone decode row costs one
token, a mixed batch costs the sum, never ``rows x max_chunk``.

Two implementations with identical semantics:

- :func:`ragged_paged_attention_ref` — pure JAX (gather + masked
  softmax), the always-correct CPU/tier-1 path and the kernel's test
  oracle. It re-gathers the owning row's pages per query token, so its
  HBM traffic is ``N * S``; fine for the CPU mesh, not the fast path.
- :func:`ragged_paged_attention` — the Pallas TPU kernel. Grid over
  ``q_tile``-sized slices of the flat stream; the caller aligns each
  row's query span to ``q_tile`` so every grid cell belongs to exactly
  one row (``tile_row``), DMAs only that row's live pages
  (``ceil(kv_len / page_size)``, double-buffered), and runs a
  flash-style online softmax in VMEM scratch. ``q_tile=1`` degenerates
  to the old per-row decode kernel (one query per grid cell — the
  shape pure-decode windows dispatch).

HBM traffic per dispatch per layer is ``sum_rows(tiles_row * kv_row)``
tokens instead of the XLA gather's ``N * S``: the kernel reads each
row's context once per query tile, never the page-bucket envelope.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tokens per double-buffered DMA chunk: amortises DMA issue cost and
# matches the MXU's 128-lane tiling for the score matmul.
_CHUNK_TOKENS = 128


def ragged_supported(
    page_size: int, num_kv_heads: int, head_dim: int, kv_dtype
) -> bool:
    """Whether this KV layout compiles on real TPU hardware.

    Mosaic tiles the last two dims of every VMEM buffer ((8, 128) for
    f32, (16, 128) for bf16) and rejects DMA slices that aren't
    tile-aligned, so the collapsed lane dim (Hkv*D) must be a multiple
    of 128 and the page size a multiple of the sublane tile. Callers
    fall back to the pure-JAX reference otherwise (interpret mode has
    no such constraint).

    This gate is part of the *resolved* attention implementation, which
    is part of the AOT compile-manifest key (docs/aot.md): a layout
    that resolves differently on another host produces a different
    manifest hash, so a warm boot can never load executables built for
    the other implementation."""
    sublane = 16 if jnp.dtype(kv_dtype).itemsize == 2 else 8
    return (num_kv_heads * head_dim) % 128 == 0 and page_size % sublane == 0


# --------------------------------------------------------------- reference
def ragged_paged_attention_ref(
    q: jnp.ndarray,  # [N, H, D] flat query stream
    k_cache: jnp.ndarray,  # [P, ps, Hkv*D] (heads collapsed into lanes)
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [R, PB] int32 (caller slices to the bucket)
    row_of: jnp.ndarray,  # [N] int32 owning row per query token
    positions: jnp.ndarray,  # [N] int32 absolute position, -1 = padding
    num_kv_heads: int | None = None,
    sm_scale: float | None = None,
    window: int | jnp.ndarray | None = None,
    softcap: float | None = None,
) -> jnp.ndarray:
    """Pure-JAX ragged paged attention (the CPU/tier-1 parity path).

    Returns ``[N, H, D]`` in q's dtype; padding tokens (position -1)
    return garbage the caller must ignore (their writes were already
    dropped, and nothing samples from them). Matmuls run in the cache
    dtype with float32 accumulation, softmax in float32 — the same
    numeric contract as ``ops/attention.paged_attention``, so a
    one-token row here is bit-identical to the old decode gather.
    ``window``/``softcap`` carry the sliding-window and tanh-capped
    score variants (mistral/gemma2) exactly as ``paged_attention``
    does — the Pallas kernel does not implement them, so those model
    families stay on this path (the engine's attn resolution enforces
    it).

    Implementation note: each flat token becomes its own T=1 batch row
    of :func:`ops.attention.paged_attention` (its row's page table
    gathered per token). Delegating keeps the reduction SHAPES — and
    therefore the float rounding — identical to the decode window's
    per-step attention, which is what keeps a mixed dispatch's logits
    bit-equal to the step-by-step schedule even at exact bf16 argmax
    ties (the greedy identity suites exercise exactly such ties on
    repetitive prompts)."""
    from .attention import paged_attention

    out = paged_attention(
        q[:, None],  # [N, 1, H, D]
        k_cache,
        v_cache,
        page_table[row_of],  # [N, PB]
        positions[:, None],  # [N, 1]
        sm_scale=sm_scale,
        window=window,
        softcap=softcap,
    )
    return out[:, 0]


# ------------------------------------------------------------------ kernel
def _ragged_kernel(
    # scalar prefetch (SMEM)
    tile_row_ref,  # [T] int32 — owning batch row per query tile
    tile_kvlen_ref,  # [T] int32 — kv tokens the tile attends over (0=skip)
    positions_ref,  # [N] int32 — absolute position per flat query
    table_ref,  # [R, Pmax] int32 — page ids per row
    # inputs
    q_ref,  # [QT, H, D] VMEM — this tile's queries
    k_hbm,  # [P, ps, Hkv*D] — page pool, stays in HBM
    v_hbm,
    # output
    o_ref,  # [QT, H, D] VMEM
    # scratch
    k_buf,  # [2, cp, ps, Hkv*D] VMEM double buffer
    v_buf,
    acc_ref,  # [H*QT, D] f32 — output accumulator, rows = (kv head, g, i)
    m_ref,  # [H*QT, 128] f32 — running max (lane-replicated)
    l_ref,  # [H*QT, 128] f32 — running sum (lane-replicated)
    sems,  # DMA semaphores [2, 2*cp]
    *,
    ps: int,
    cp: int,
    hkv: int,
    hd: int,
    qpk: int,
    qt: int,
    pmax: int,
    scale: float,
):
    t = pl.program_id(0)
    row = tile_row_ref[t]
    kvlen = tile_kvlen_ref[t]
    n_chunks = pl.cdiv(kvlen, ps * cp)

    def chunk_dmas(c, slot):
        """The 2*cp page copies of chunk ``c`` into buffer ``slot``.

        Page indices beyond the row's table are clamped to a valid
        entry: the DMA still runs (keeping semaphore accounting static)
        and the tokens are masked out of the softmax below. (Hkv, D)
        are pre-collapsed into one lane dimension so every copy slices
        only leading (untiled) dims — Mosaic rejects slices of a lane
        dim narrower than the 128-lane tile."""
        dmas = []
        base = c * cp
        for j in range(cp):
            idx = jnp.minimum(base + j, pmax - 1)
            pid = table_ref[row, idx]
            dmas.append(
                pltpu.make_async_copy(
                    k_hbm.at[pid], k_buf.at[slot, j], sems.at[slot, 2 * j]
                )
            )
            dmas.append(
                pltpu.make_async_copy(
                    v_hbm.at[pid], v_buf.at[slot, j], sems.at[slot, 2 * j + 1]
                )
            )
        return dmas

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, -1e30)
    l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(n_chunks > 0)
    def _():
        for dma in chunk_dmas(0, 0):
            dma.start()

    # Per-query absolute positions (the causal bound), read from SMEM.
    # Padding queries carry -1: nothing satisfies kv_pos <= -1, their
    # softmax sum stays 0 and the final divide maps them to zeros.
    pos_col = jnp.stack(
        [positions_ref[t * qt + i] for i in range(qt)]
    )  # [QT]
    # Score rows are laid out (kv head, group, query): each head's
    # block is contiguous, and within it the query index varies
    # fastest — so the per-query causal bound tiles as [qpk*QT].
    pos_rows = jnp.tile(pos_col, qpk)[:, None]  # [qpk*QT, 1]

    # [QT, H, D] -> [H', QT, D] with H' rows ordered (kv head, group):
    # per-kv-head slices are then contiguous row blocks.
    q = jnp.swapaxes(q_ref[...].astype(jnp.float32), 0, 1)  # [H, QT, D]
    S = cp * ps

    def body(c, _):
        slot = jax.lax.rem(c, 2)
        next_slot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            for dma in chunk_dmas(c + 1, next_slot):
                dma.start()

        for dma in chunk_dmas(c, slot):
            dma.wait()

        tok_idx = c * S + jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        k = k_buf[slot].reshape(S, hkv * hd)  # [S, Hkv*D]
        v = v_buf[slot].reshape(S, hkv * hd)
        for h in range(hkv):
            rows = slice(h * qpk * qt, (h + 1) * qpk * qt)
            cols = slice(h * hd, (h + 1) * hd)
            qh = q[h * qpk : (h + 1) * qpk].reshape(qpk * qt, hd)
            kh = k[:, cols].astype(jnp.float32)  # [S, D]
            s = (
                jax.lax.dot_general(
                    qh,
                    kh,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [qpk*QT, S]
            s = jnp.where(tok_idx <= pos_rows, s, -1e30)
            m_prev = m_ref[rows, :1]  # [qpk*QT, 1]
            m_cur = jnp.max(s, axis=1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_ref[rows, :] = l_ref[rows, :] * alpha + jnp.sum(
                p, axis=1, keepdims=True
            )
            pv = jax.lax.dot_general(
                p.astype(v.dtype),
                v[:, cols],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [qpk*QT, D]
            acc_ref[rows, :] = acc_ref[rows, :] * alpha + pv
            m_ref[rows, :] = jnp.broadcast_to(m_new, m_ref[rows, :].shape)
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)

    l = l_ref[:, :1]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc_ref[...] / l_safe  # [H*QT, D], rows (kv head, g, i)
    out = out.reshape(hkv, qpk, qt, hd).transpose(2, 0, 1, 3)
    o_ref[...] = out.reshape(qt, hkv * qpk, hd).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("num_kv_heads", "q_tile", "sm_scale", "interpret"),
)
def ragged_paged_attention(
    q: jnp.ndarray,  # [N, H, D] flat query stream (N % q_tile == 0)
    k_cache: jnp.ndarray,  # [P, ps, Hkv*D] (heads collapsed into lanes)
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [R, Pmax] int32
    row_of: jnp.ndarray,  # [N] int32 owning row per query token
    positions: jnp.ndarray,  # [N] int32 absolute position, -1 = padding
    num_kv_heads: int | None = None,
    q_tile: int = 8,
    sm_scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged paged attention over a flat query stream (Pallas TPU).

    The caller aligns each row's query span to ``q_tile`` flat slots
    (padding tokens carry position -1), so every tile belongs to
    exactly one row — each grid cell DMAs only that row's live pages
    and computes ``q_tile`` queries against them. ``q_tile=1`` is the
    pure-decode shape (one query per row, the old paged-decode kernel's
    grid). Returns [N, H, D] in q's dtype. Padding slots inside a live
    tile return unspecified values the caller must ignore (their KV
    writes were dropped and nothing samples from them); fully-empty
    tiles (inactive rows) return exact zeros.

    The caller guarantees the fed tokens' K/V are already written
    (write-then-gather), so a tile's DMA bound is its max position + 1.
    """
    N, H, D = q.shape
    _, ps, fused = k_cache.shape
    Hkv = num_kv_heads if num_kv_heads is not None else fused // D
    pmax = page_table.shape[1]
    qpk = H // Hkv
    scale = sm_scale if sm_scale is not None else D**-0.5
    cp = max(1, min(_CHUNK_TOKENS // ps, pmax))
    qt = q_tile
    n_tiles = N // qt

    # Per-tile row + DMA bound, derived on device from the flat stream
    # (alignment makes every tile single-row; padding positions are -1
    # so the max is the tile's true causal horizon).
    tile_row = row_of.reshape(n_tiles, qt)[:, 0]
    tile_kvlen = positions.reshape(n_tiles, qt).max(axis=1) + 1

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (qt, H, D), lambda t, *_: (t, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (qt, H, D), lambda t, *_: (t, 0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((2, cp, ps, Hkv * D), k_cache.dtype),
            pltpu.VMEM((2, cp, ps, Hkv * D), v_cache.dtype),
            pltpu.VMEM((H * qt, D), jnp.float32),
            pltpu.VMEM((H * qt, 128), jnp.float32),
            pltpu.VMEM((H * qt, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2 * cp)),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        ps=ps,
        cp=cp,
        hkv=Hkv,
        hd=D,
        qpk=qpk,
        qt=qt,
        pmax=pmax,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, H, D), q.dtype),
        interpret=interpret,
    )(tile_row, tile_kvlen, positions, page_table, q, k_cache, v_cache)


def ragged_decode_attention(
    q: jnp.ndarray,  # [B, H, D] — one query per row
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    page_table: jnp.ndarray,  # [B, Pmax] int32
    lengths: jnp.ndarray,  # [B] int32 tokens to attend over (0 = inactive)
    num_kv_heads: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pure-decode shape of the ragged kernel: one query per row at
    ``q_tile=1`` (row b attends its own ``lengths[b]`` tokens; rows
    with length 0 return zeros). This is the shape every step of a
    compiled decode window dispatches."""
    B = q.shape[0]
    row_of = jnp.arange(B, dtype=jnp.int32)
    return ragged_paged_attention(
        q,
        k_cache,
        v_cache,
        page_table,
        row_of,
        lengths - 1,  # position of the newest written token
        num_kv_heads=num_kv_heads,
        q_tile=1,
        interpret=interpret,
    )
