"""Echo test engines: validate the full serving pipeline without a model.

Capability parity with the reference's echo engines
(``/root/reference/lib/llm/src/engines.rs:81-122``): the core variant
echoes prompt token ids back one per step (exercising detokenization and
stop handling); the full variant echoes the last user message as text
(exercising the OpenAI chunk path).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator

from ..protocols.common import BackendInput, FinishReason, LLMEngineOutput
from ..protocols.delta import ChatDeltaGenerator, CompletionDeltaGenerator
from ..protocols.openai import ChatCompletionRequest, CompletionRequest
from ..runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream


class EchoEngineCore(AsyncEngine):
    """Token-level echo: streams the prompt's token ids back, one per step."""

    def __init__(self, token_delay_ms: float = 0.0):
        self.token_delay_ms = token_delay_ms

    async def generate(
        self, request: dict, context: AsyncEngineContext | None = None
    ) -> ResponseStream[dict]:
        ctx = context or AsyncEngineContext()
        binput = BackendInput.model_validate(request)

        async def _gen() -> AsyncIterator[dict]:
            limit = binput.stop_conditions.max_tokens or len(binput.token_ids)
            for i, tid in enumerate(binput.token_ids):
                if ctx.is_stopped or i >= limit:
                    break
                if self.token_delay_ms:
                    await asyncio.sleep(self.token_delay_ms / 1000.0)
                yield LLMEngineOutput(token_ids=[tid]).to_dict()
            yield LLMEngineOutput(
                finish_reason=FinishReason.LENGTH,
                prompt_tokens=len(binput.token_ids),
                completion_tokens=min(limit, len(binput.token_ids)),
            ).to_dict()

        return ResponseStream(_gen(), ctx)


class EchoEngineFull(AsyncEngine):
    """OpenAI-level echo: streams the last user message back as text."""

    def __init__(self, token_delay_ms: float = 0.0, chunk_chars: int = 4):
        self.token_delay_ms = token_delay_ms
        self.chunk_chars = chunk_chars

    async def generate(
        self, request: dict, context: AsyncEngineContext | None = None
    ) -> ResponseStream[dict]:
        ctx = context or AsyncEngineContext()
        if "messages" in request:
            req = ChatCompletionRequest.model_validate(request)
            text = next(
                (
                    m.text_content()
                    for m in reversed(req.messages)
                    if m.role == "user"
                ),
                "",
            )
            gen = ChatDeltaGenerator(req.model, ctx.id)
        else:
            req = CompletionRequest.model_validate(request)
            text = req.prompt if isinstance(req.prompt, str) else ""
            gen = CompletionDeltaGenerator(req.model, ctx.id)

        async def _gen() -> AsyncIterator[dict]:
            for i in range(0, len(text), self.chunk_chars):
                if ctx.is_stopped:
                    break
                if self.token_delay_ms:
                    await asyncio.sleep(self.token_delay_ms / 1000.0)
                yield gen.text_chunk(text[i : i + self.chunk_chars]).model_dump(
                    exclude_none=True
                )
            yield gen.finish_chunk(FinishReason.EOS).model_dump(exclude_none=True)

        return ResponseStream(_gen(), ctx)
