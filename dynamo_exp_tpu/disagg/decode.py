"""Decode-side disaggregation: the conditional remote-prefill engine.

Reference parity: ``examples/llm/components/worker.py:180-229`` — per
request, decide local vs remote prefill from (uncached prefill length,
prefill queue depth, live DisaggConfig); on remote, enqueue the work and
hand the engine the prefilled KV. Failure story: any transfer problem
falls back to local prefill — disaggregation is an optimization, never a
correctness dependency.
"""

from __future__ import annotations

import logging

from ..engine.engine import TPUEngine
from ..engine.scheduler import RemoteKv
from ..protocols.common import BackendInput
from ..runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream
from ..runtime.health import CircuitBreaker
from ..runtime.transports.base import WorkQueue
from ..telemetry import get_telemetry, span as trace_span
from .config import DisaggConfigWatcher
from .protocol import RemotePrefillRequest, kv_signature
from .transfer import KvPageReceiver

logger = logging.getLogger(__name__)


class DisaggDecodeEngine(AsyncEngine):
    """Wraps a TPUEngine; long uncached prefills are offloaded to the
    prefill fleet through the work queue + KV transfer plane.

    A circuit breaker guards the remote path: when the prefill fleet is
    dead, every offload attempt burns ``transfer_timeout_s`` of TTFT
    before falling back — after ``breaker``'s threshold of consecutive
    failures new requests prefill locally immediately, with a half-open
    probe re-testing the fleet each cooldown."""

    def __init__(
        self,
        engine: TPUEngine,
        queue: WorkQueue,
        receiver: KvPageReceiver,
        config: DisaggConfigWatcher,
        transfer_timeout_s: float = 60.0,
        breaker: CircuitBreaker | None = None,
    ):
        self.engine = engine
        self.queue = queue
        self.receiver = receiver
        self.config = config
        self.transfer_timeout_s = transfer_timeout_s
        self.breaker = breaker or CircuitBreaker(name="remote-prefill")
        self.remote_prefills = 0  # metrics
        self.local_fallbacks = 0
        self.queue_probe_failures = 0
        # Suffix-only transfers: prompt blocks NOT shipped because this
        # decode worker already held them (docs/prefix_sharing.md).
        self.blocks_skipped = 0

    async def generate(
        self, request: dict | BackendInput, context: AsyncEngineContext | None = None
    ) -> ResponseStream[dict]:
        ctx = context or AsyncEngineContext()
        ctx.check_deadline("decode")
        binput = (
            request
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        remote_kv = None
        # Breaker state first (would_allow doesn't claim the half-open
        # probe slot): with the fleet dead, requests must go local
        # without even paying the queue.size() round-trip.
        if (
            self.breaker.would_allow()
            and await self._should_prefill_remote(binput)
            and self.breaker.allow()
        ):
            try:
                remote_kv = await self._remote_prefill(binput, ctx)
            except BaseException:
                # _remote_prefill records success/failure for everything
                # it catches; only cancellation (and kin) escapes — free
                # the claimed half-open probe slot without an outcome.
                self.breaker.release()
                raise
        return await self.engine.generate(binput, ctx, remote_kv=remote_kv)

    async def _should_prefill_remote(self, binput: BackendInput) -> bool:
        cfg = self.config.current()
        # The router annotates its prefix-overlap estimate; subtract the
        # cached part so the decision weighs actual prefill compute
        # (reference: worker.py:184-198).
        cached = (binput.estimated_prefix_hit_num_blocks or 0) * self.engine.cfg.page_size
        prefill_len = max(len(binput.token_ids) - cached, 0)
        if prefill_len <= cfg.max_local_prefill_length:
            return False
        try:
            queue_size = await self.queue.size()
        except Exception:  # noqa: BLE001 - a broken queue means "no fleet":
            # prefill locally, per the module's best-effort contract. The
            # request must not die because an optimization's control
            # plane is down.
            logger.warning(
                "prefill queue size probe failed; prefilling locally",
                exc_info=True,
            )
            self.queue_probe_failures += 1
            return False
        return cfg.prefill_remote(prefill_len, queue_size)

    async def _remote_prefill(
        self, binput: BackendInput, ctx: AsyncEngineContext
    ) -> RemoteKv | None:
        """Queue the prefill and await its KV; None means do it locally."""
        import asyncio

        rid = ctx.id
        fut = self.receiver.expect(rid)
        # The transfer wait never outlives the request's own deadline.
        timeout = self.transfer_timeout_s
        remaining = ctx.time_remaining()
        if remaining is not None:
            timeout = min(timeout, max(remaining, 0.0))
        # Suffix-only transfer (docs/prefix_sharing.md): pin the locally
        # resident shared prefix so the wire ships only the unshared
        # suffix. The pin (a KV lease) keeps those pages resident until
        # admission re-references them; at least the last page always
        # ships (it carries the partial tail + proves the worker ran).
        need_pages = -(-len(binput.token_ids) // self.engine.cfg.page_size)
        skip, pin_lease = 0, None
        try:
            skip, pin_lease = await self.engine.pin_prefix(binput.token_ids)
        except Exception:  # noqa: BLE001 - the pin is an optimization
            logger.warning("prefix pin failed; full transfer", exc_info=True)
        skip = min(skip, max(need_pages - 1, 0))
        with trace_span(
            "remote_prefill",
            request_id=rid,
            prompt_tokens=len(binput.token_ids),
            skipped_blocks=skip or None,
            # Failover continuation (prompt + journaled tokens being
            # re-prefilled) — visible in `llmctl trace` as the re-prefill
            # hop's remote leg.
            resumed_tokens=binput.resume_offset or None,
        ) as sp:
            # The span's own context rides the queue, so the prefill
            # worker's spans (engine queue wait, prefill compute, KV
            # transfer send) land under this node of the trace.
            req = RemotePrefillRequest(
                request_id=rid,
                token_ids=list(binput.token_ids),
                return_addr=self.receiver.address,
                sampling_options=binput.sampling_options.model_dump(
                    exclude_none=True
                ),
                page_size=self.engine.cfg.page_size,
                model=kv_signature(self.engine.cfg),
                trace_id=sp.context.trace_id,
                parent_span_id=sp.context.span_id,
                deadline_unix=ctx.deadline or 0.0,
                skip_blocks=skip,
                # Per-link transfer ledger: the prefill worker records
                # the (src, dst) link by instance name, not by this
                # process's ephemeral receiver port.
                decode_instance=get_telemetry().instance,
            )
            try:
                await self.queue.push(req.to_bytes())
                first_token, pages = await asyncio.wait_for(
                    fut, timeout=timeout
                )
                skip_used = self._check_page_shapes(
                    pages, len(binput.token_ids), skip
                )
                self.remote_prefills += 1
                self.blocks_skipped += skip_used
                self.breaker.record_success()
                sp.set(outcome="remote", skipped_blocks=skip_used or None)
                return RemoteKv(
                    first_token=first_token,
                    pages=pages,
                    skip_pages=skip_used,
                    pin_lease=pin_lease,
                )
            except Exception:  # noqa: BLE001 - remote prefill is best-effort
                logger.exception(
                    "remote prefill failed for %s; prefilling locally", rid
                )
                self.receiver.forget(rid)
                self.local_fallbacks += 1
                if pin_lease:
                    # Local prefill will re-match (or recompute) the
                    # prefix itself; release the routing-time pin.
                    self.engine.confirm_kv_lease(pin_lease)
                # A wait cut short by the *request's own deadline* says
                # nothing about fleet health — only count fleet-attributable
                # failures toward the breaker, or three short-deadline
                # requests would lock healthy remote prefill out for a
                # whole cooldown. But allow() may have claimed the
                # half-open probe slot: a no-outcome exit must RELEASE
                # it, or the breaker sticks in HALF_OPEN and remote
                # prefill is locked out forever (ROADMAP open item).
                if not ctx.deadline_expired:
                    self.breaker.record_failure()
                else:
                    self.breaker.release()
                sp.set(outcome="local_fallback")
                return None
            except BaseException:
                # Cancellation (client disconnect / deadline) must not
                # strand the suffix-transfer pin until the lease TTL —
                # under a burst of cancelled long-prefix requests that
                # transiently shrinks the decode pool for live work.
                self.receiver.forget(rid)
                if pin_lease:
                    self.engine.confirm_kv_lease(pin_lease)
                raise

    def _check_page_shapes(
        self, pages: list, prompt_len: int, skip: int = 0
    ) -> int:
        """Last line of defense: a wrong-shaped or short transfer must
        fall back to local prefill here, not leave uninitialized device
        pages that decode silently attends over. Returns the skip the
        sender actually honored: a full-length reply (older worker that
        ignores ``skip_blocks``) is accepted as skip 0."""
        cfg = self.engine.cfg
        need = (prompt_len + cfg.page_size - 1) // cfg.page_size
        if len(pages) == need:
            skip_used = 0  # full transfer (skip ignored or 0)
        elif skip and len(pages) == need - skip:
            skip_used = skip  # suffix-only transfer
        else:
            raise ValueError(
                f"got {len(pages)} KV pages, expected {need} "
                f"(or {need - skip} with skip_blocks={skip})"
            )
        expected = (
            cfg.model.num_layers,
            cfg.page_size,
            cfg.model.num_kv_heads * cfg.model.head_dim_,
        )
        for k, v in pages:
            if tuple(k.shape) != expected or tuple(v.shape) != expected:
                raise ValueError(
                    f"KV page shape {tuple(k.shape)} != expected {expected}"
                )
        return skip_used

    def metrics(self) -> dict:
        m = self.engine.metrics()
        m["disagg_remote_prefills"] = self.remote_prefills
        m["disagg_local_fallbacks"] = self.local_fallbacks
        m["disagg_queue_probe_failures"] = self.queue_probe_failures
        m["disagg_blocks_skipped"] = self.blocks_skipped
        m["disagg_breaker_state"] = self.breaker.state.value
        return m
