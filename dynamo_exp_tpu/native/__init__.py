"""Native runtime tier: C++ extensions built on demand with the host
toolchain, loaded via ctypes, each with an exact-mirror Python fallback.

The reference's runtime hot paths are native (Rust tokens/codec, CUDA
block movement — SURVEY.md §2.1/§2.2); here the TPU compute path is
XLA/Pallas and the host-side hot paths go through this package. The
fallback is not an approximation: it implements the same bit-exact
algorithm, because hashes cross process boundaries (router vs worker)
and both sides must agree regardless of which implementation ran.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_blockhash.so")
_SRC = os.path.join(_HERE, "blockhash.cpp")

_M = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15
_LOCAL_TAG = 0x00B10C4A54AA17E5
_CHAIN_TAG = 0x00C4A18A54BB28F6
_NO_PARENT_TAG = 0x006E6F5061726E74


def _build() -> bool:
    """Compile blockhash.cpp → _blockhash.so (atomic, race-safe: build
    to a temp file and os.replace). Returns False when no compiler or
    the package directory is read-only — callers fall back to Python."""
    tmp_path = None
    try:
        with tempfile.NamedTemporaryFile(
            dir=_HERE, suffix=".so", delete=False
        ) as tmp:
            tmp_path = tmp.name
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp_path, _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp_path, _SO_PATH)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native blockhash build failed (%s); using Python", e)
        if tmp_path is not None:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
        return False


def _stale() -> bool:
    """The built .so predates the source — rebuild, or a process with
    the old binary would hash differently from freshly built peers
    (the 'change both or neither' contract in blockhash.cpp)."""
    try:
        return os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC)
    except OSError:
        return True


def _load():
    if (not os.path.exists(_SO_PATH) or _stale()) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:  # stale/foreign-arch .so — rebuild once
        if not _build():
            return None
        lib = ctypes.CDLL(_SO_PATH)
    u64, u32p, i32 = ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint32), ctypes.c_int
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.dx_block_hash.restype = u64
    lib.dx_block_hash.argtypes = [u32p, u64, u64]
    lib.dx_chain_hash.restype = u64
    lib.dx_chain_hash.argtypes = [u64, i32, u64, u64]
    lib.dx_seq_hashes.restype = u64
    lib.dx_seq_hashes.argtypes = [u32p, u64, u64, u64, i32, u64, u64p]
    return lib


_lib = None
_loaded = False


def _get_lib():
    """Lazy load: the (possibly g++-compiling) load happens on the first
    hash call, not at import — a fleet of worker processes importing
    tokens.py must not each stall on a synchronous compile at startup."""
    global _lib, _loaded
    if not _loaded:
        _lib = _load()
        _loaded = True
    return _lib


def native_available() -> bool:
    return _get_lib() is not None


# ---------------------------------------------------------------- fallback
def _mix64(x: int) -> int:
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M
    x ^= x >> 31
    return x


def _py_block_hash(tokens, seed: int) -> int:
    h = _mix64((seed & _M) ^ _LOCAL_TAG)
    for t in tokens:
        h = _mix64(h ^ ((int(t) + _GOLDEN) & _M))
    return _mix64(h ^ len(tokens))


def _py_chain_hash(parent: int | None, local: int, seed: int) -> int:
    h = _mix64((seed & _M) ^ _CHAIN_TAG)
    h = _mix64(h ^ (_NO_PARENT_TAG if parent is None else parent & _M))
    return _mix64(h ^ (local & _M))


# --------------------------------------------------------------- public API
def block_hash(tokens, seed: int) -> int:
    _lib = _get_lib()
    if _lib is None:
        return _py_block_hash(tokens, seed)
    import numpy as np

    arr = np.ascontiguousarray(tokens, dtype=np.uint32)
    return int(
        _lib.dx_block_hash(
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(arr),
            seed & _M,
        )
    )


def chain_hash(parent: int | None, local: int, seed: int) -> int:
    _lib = _get_lib()
    if _lib is None:
        return _py_chain_hash(parent, local, seed)
    return int(
        _lib.dx_chain_hash(
            0 if parent is None else parent & _M,
            0 if parent is None else 1,
            local & _M,
            seed & _M,
        )
    )


def seq_hashes(
    tokens, block_size: int, seed: int, parent: int | None = None
) -> list[int]:
    """Sequence hashes of every complete block — one native call for the
    whole prompt instead of a Python loop per block."""
    _lib = _get_lib()
    if _lib is None:
        out: list[int] = []
        p = parent
        for start in range(0, len(tokens) - block_size + 1, block_size):
            local = _py_block_hash(tokens[start : start + block_size], seed)
            p = _py_chain_hash(p, local, seed)
            out.append(p)
        return out
    import numpy as np

    arr = np.ascontiguousarray(tokens, dtype=np.uint32)
    nb = len(arr) // block_size
    if nb == 0:
        return []
    out_arr = np.empty(nb, dtype=np.uint64)
    n = _lib.dx_seq_hashes(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(arr),
        block_size,
        seed & _M,
        0 if parent is None else 1,
        0 if parent is None else parent & _M,
        out_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return [int(h) for h in out_arr[:n]]
