"""Per-dispatch device profiling (docs/observability.md).

Covers the profiler acceptance path: every one of the three dispatch
kinds — ragged compute batches (prefill chunks, decode windows, and
spec verify spans all ride ``kind="ragged"``), KV gather/scatter, and
eviction offload batches — gets host-gap/in-flight/compile attribution
during one mixed run and surfaces on ``/metrics``; the decode span
carries dispatch attrs ``sim/fit.py`` can fit from; and the overhead
guarantee holds: profiling adds ZERO host syncs to the decode path
(sync-spy shim counting jax→numpy materializations, not wall clock —
CPU timing is load-sensitive).
"""

import asyncio

import numpy as np
import pytest

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput
from dynamo_exp_tpu.telemetry import get_telemetry
from dynamo_exp_tpu.telemetry.dispatch import (
    DISPATCH_KINDS,
    SUMMARY_FIELDS,
    DispatchProfiler,
)

PS = 8


def _cfg(**over) -> EngineConfig:
    base = dict(
        model=TINY,
        max_decode_slots=2,
        page_size=PS,
        num_pages=64,
        max_model_len=128,
        eos_token_ids=[],
        kv_dtype="float32",
        decode_window=4,
    )
    return EngineConfig(**(base | over))


async def _generate(engine, prompt, max_tokens=8):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = max_tokens
    b.stop_conditions.ignore_eos = True
    stream = await engine.generate(b.to_dict())
    n = 0
    async for item in stream:
        n += len(item.get("token_ids", []))
    return n


# ------------------------------------------------------------------- units
def test_profiler_summary_shape_is_stable():
    prof = DispatchProfiler()
    s = prof.summary()
    assert set(s) == set(DISPATCH_KINDS)
    for stats in s.values():
        assert set(stats) == set(SUMMARY_FIELDS)
        assert stats["count"] == 0 and stats["in_flight_p50_s"] is None


def test_profiler_gap_in_flight_and_compile_accounting():
    prof = DispatchProfiler()
    t0 = prof.begin("ragged")
    t_disp = prof.end("ragged", t0, fresh=True)
    prof.consume("ragged", t_disp)
    # Second dispatch: the gap since the consume is now measurable.
    t1 = prof.begin("ragged")
    t_disp = prof.end("ragged", t1, fresh=False)
    prof.consume("ragged", t_disp)
    s = prof.summary()["ragged"]
    assert s["count"] == 2
    assert s["compile_misses"] == 1 and s["compile_total_s"] >= 0.0
    assert s["in_flight_p50_s"] is not None
    assert s["host_gap_p50_s"] is not None


def test_profiler_idle_drops_gap_reference():
    prof = DispatchProfiler()
    t0 = prof.begin("ragged")
    prof.consume("ragged", prof.end("ragged", t0))
    prof.mark_idle()
    prof.begin("ragged")  # would be a huge gap if the mark survived idle
    assert prof.summary()["ragged"]["host_gap_p50_s"] is None


def test_first_variant_is_once_per_key():
    prof = DispatchProfiler()
    assert prof.first_variant("gather", 8)
    assert not prof.first_variant("gather", 8)
    assert prof.first_variant("gather", 16)
    assert prof.first_variant("scatter", 8)


# --------------------------------------------- all five kinds, one engine
@pytest.mark.nightly
async def test_all_dispatch_kinds_profiled_in_mixed_run():
    """Acceptance: a mixed prefill+decode+spec run (plus the disagg
    extract and an eviction burst the same engine serves) populates
    dispatch/host-gap timing for ALL three kinds, and the per-kind
    series surface on the telemetry registry ``/metrics`` renders."""
    cfg = _cfg(
        num_pages=8,  # tight pool: the second prompt evicts the first's
        host_cache_pages=16,  # parked pages -> offload batch
        spec_mode="ngram",
        spec_draft_len=4,
        spec_adaptive=False,
    )
    engine = TPUEngine(cfg, mesh=single_device_mesh(), seed=0)
    engine.start()
    try:
        # Prefill + decode, pages registered then parked at finish.
        await _generate(engine, range(20, 36), max_tokens=6)
        # Repetitive prompt: the n-gram drafter proposes (spec spans
        # in the mixed ragged dispatch),
        # and its 6-page allocation evicts parked pages (offload).
        block = [50, 51, 52, 53, 54, 55, 56, 57]
        await _generate(engine, block * 6, max_tokens=8)
        # Disagg prefill-extract: batched gather + existing sync
        # (kv_move), pages pinned under a lease we confirm.
        _tok, pages, lease = await engine.prefill_extract(
            BackendInput(token_ids=list(range(100, 116))).to_dict()
        )
        assert pages and lease
        engine.confirm_kv_lease(lease)
        if engine.copy_stream is not None:
            engine.copy_stream.drain()

        disp = engine.metrics()["dispatch"]
        for kind in DISPATCH_KINDS:
            assert disp[kind]["count"] > 0, f"{kind} never dispatched"
        # Synced kinds carry in-flight samples (scatter-only moves
        # would not, but extract's gather is synced).
        for kind in ("ragged", "kv_move", "offload"):
            assert disp[kind]["in_flight_p50_s"] is not None, kind
        # Compile attribution: the ragged variant cache missed at least
        # once this run. The page-move gather shapes are ONE jit shared
        # by kv_move and offload, so the miss lands on whichever kind
        # dispatched the bucket first — assert across the pair, not per
        # kind.
        assert disp["ragged"]["compile_misses"] >= 1
        assert (
            disp["kv_move"]["compile_misses"]
            + disp["offload"]["compile_misses"]
        ) >= 1

        rendered = get_telemetry().render().decode()
        for kind in DISPATCH_KINDS:
            assert f'dynamo_dispatch_seconds_count{{kind="{kind}"}}' in rendered
            assert f'kind="{kind}"' in rendered
        assert "dynamo_host_gap_seconds_bucket" in rendered
        assert "dynamo_compile_seconds_bucket" in rendered
        assert "dynamo_compile_cache_misses_total" in rendered
    finally:
        engine.stop()


# ------------------------------------------------------- span integration
async def test_decode_span_carries_dispatch_attrs_and_fit_reads_them(tmp_path):
    from dynamo_exp_tpu.telemetry import span

    tel = get_telemetry()
    trace_file = str(tmp_path / "trace.jsonl")
    tel.configure(trace_file)
    engine = TPUEngine(_cfg(), mesh=single_device_mesh(), seed=0)
    engine.start()
    try:
        # The engine stamps spans onto the trace captured at
        # submission — open one like the HTTP root span would.
        with span("test_root"):
            b = BackendInput(token_ids=list(range(30, 46)))
            b.stop_conditions.max_tokens = 8
            b.stop_conditions.ignore_eos = True
            stream = await engine.generate(b.to_dict())
        async for _ in stream:
            pass
    finally:
        engine.stop()
        tel.configure(None)
    from dynamo_exp_tpu.telemetry import load_spans

    decode = [s for s in load_spans([trace_file]) if s.stage == "decode"]
    assert decode, "no decode span recorded"
    attrs = decode[-1].attrs
    assert attrs["dispatch_p50_s"] > 0
    assert attrs["decode_window"] == 4
    assert "host_gap_p50_s" in attrs

    from dynamo_exp_tpu.sim.fit import ServiceTimeModel

    model = ServiceTimeModel.from_spans([trace_file])
    assert model.itl_s.median_s > 0


def test_bench_dispatch_stats_fit_without_throughput_metric(tmp_path):
    """A bench line with no concurrency-tagged metric still fits ITL
    from its per-kind dispatch percentiles + decode_window — from the
    ragged engine's ``kind="ragged"`` lines AND (back-compat) from
    pre-ragged ``BENCH_r*.json`` lines that carry the old ``decode``
    kind."""
    import json

    from dynamo_exp_tpu.sim.fit import ServiceTimeModel

    def line(kind, flight):
        return {
            "metric": "custom_point",
            "value": 1.0,
            "decode_window": 8,
            "dispatch": {
                kind: {
                    "count": 10,
                    "in_flight_p50_s": flight,
                    "host_gap_p50_s": 0.008,
                }
            },
        }

    old = tmp_path / "bench_old.json"
    old.write_text(json.dumps(line("decode", 0.08)) + "\n")
    model = ServiceTimeModel.from_bench_json([old])
    assert model.itl_s.median_s == pytest.approx((0.08 + 0.008) / 8)

    new = tmp_path / "bench_ragged.json"
    new.write_text(json.dumps(line("ragged", 0.16)) + "\n")
    model = ServiceTimeModel.from_bench_json([new])
    assert model.itl_s.median_s == pytest.approx((0.16 + 0.008) / 8)


# ------------------------------------------------------- overhead (sync spy)
@pytest.mark.nightly
def test_profiler_adds_zero_host_syncs_per_window(monkeypatch):
    """Overhead smoke (`make profile-smoke`): the instrumented decode
    path performs ZERO additional host syncs — the same workload runs
    with profiling on and off under a sync-spy shim counting
    jax-Array→numpy materializations, and the counts must be equal
    (wall clock is deliberately not compared; CPU timing is
    load-sensitive)."""
    import jax

    def run_counted(profile: bool) -> tuple[int, int]:
        engine = TPUEngine(
            _cfg(profile_dispatches=profile),
            mesh=single_device_mesh(),
            seed=0,
        )
        engine.start()
        counter = {"n": 0}
        orig = np.asarray

        def spy(a, *args, **kw):
            if isinstance(a, jax.Array):
                counter["n"] += 1
            return orig(a, *args, **kw)

        monkeypatch.setattr(np, "asarray", spy)
        try:
            asyncio.run(_generate(engine, range(40, 56), max_tokens=12))
        finally:
            monkeypatch.setattr(np, "asarray", orig)
            engine.stop()
        return counter["n"], engine.steps

    syncs_on, steps_on = run_counted(True)
    syncs_off, steps_off = run_counted(False)
    assert steps_on == steps_off  # identical window schedule
    assert syncs_on == syncs_off, (
        f"profiling changed host-sync count: {syncs_on} vs {syncs_off}"
    )
    assert syncs_on > 0  # the spy actually saw the consume syncs


# ---------------------------------------------------------- compile guard
async def test_compile_misses_stop_in_steady_state():
    engine = TPUEngine(_cfg(), mesh=single_device_mesh(), seed=0)
    engine.start()
    try:
        await _generate(engine, range(20, 36), max_tokens=8)
        first = {
            k: v["compile_misses"]
            for k, v in engine.metrics()["dispatch"].items()
        }
        assert first["ragged"] >= 2  # prefill-shaped + windowed variants
        # Same shapes again: every variant is cached, misses must not move.
        await _generate(engine, range(60, 76), max_tokens=8)
        second = {
            k: v["compile_misses"]
            for k, v in engine.metrics()["dispatch"].items()
        }
        assert second == first
    finally:
        engine.stop()
