"""Durable G3 KV tier tests (docs/fault_tolerance.md "Durable KV &
corruption containment"): PersistentKvStore crash-consistency units
(atomic writes, torn-tail detection, manifest replay, quarantine,
degradation ladder, the O(1) conservation ledger), restart-identical
prefix re-attachment end to end (hard-kill an engine mid-conversation,
boot a fresh process over the same store, prove the persist hit and
token identity — greedy and seeded), seeded storage-fault containment
(``make chaos`` STORE_SEED_SETS: bit-flip, torn tail, ENOSPC, slow
reads, missing store dir — no token from a corrupt page, no hangs,
token-identical to fault-free), the stop()-drain regression (pending
G2 demotions flush through the G3 writer, never past a wedged loop),
the wire-checksum unit, and the sim restart drill.

The identity proofs follow the tiering-suite pattern: counter-based
sampling makes tokens a pure function of the request, and the G3 round
trip is bit-exact under ``kv_dtype=float32`` — so a restored prefix
must decode identically to recompute, and a quarantined page's journal
re-prefill is token-identical by construction. The autouse conservation
guard (tests/conftest.py) polices both the page ledger and the G3
ledger (stop() folds ``g3_store.ledger_check()`` into the audit).
"""

import asyncio
import os
import time
import zlib

import numpy as np
import pytest

from dynamo_exp_tpu.engine import EngineConfig, TPUEngine
from dynamo_exp_tpu.kv.persistent import _HEADER, PersistentKvStore
from dynamo_exp_tpu.models import TINY
from dynamo_exp_tpu.parallel import single_device_mesh
from dynamo_exp_tpu.protocols.common import BackendInput
from dynamo_exp_tpu.runtime.transports.chaos import StorageChaos

PS = 8

CHAOS_SEEDS = [
    int(s) for s in os.environ.get("CHAOS_SEEDS", "7").split(",")
]

# ------------------------------------------------------------- store units
SHAPE = (2, 4, 8)


def _store(root, cap=8, chaos=None):
    return PersistentKvStore(str(root), cap, SHAPE, np.float32, chaos=chaos)


def _page(i):
    return (
        np.full(SHAPE, float(i), np.float32),
        np.full(SHAPE, float(-i), np.float32),
    )


def test_store_roundtrip_refresh_and_lru_eviction(tmp_path):
    st = _store(tmp_path, cap=2)
    assert st.store(1, *_page(1))
    assert st.store(2, *_page(2))
    k, v = st.fetch(1)
    np.testing.assert_array_equal(k, _page(1)[0])
    np.testing.assert_array_equal(v, _page(1)[1])
    assert st.hits == 1
    # Re-store of a resident hash refreshes, never duplicates.
    assert st.store(1, *_page(1))
    assert st.refreshes == 1 and st.stores == 2
    # Third page over a 2-page capacity: insertion-order LRU evicts the
    # coldest (hash 2 — hash 1 was refreshed above), file and all.
    assert st.store(3, *_page(3))
    assert st.evictions == 1
    assert st.fetch(2) is None and st.misses == 1
    assert 2 not in st and 1 in st and 3 in st
    assert st.ledger_check() == []
    st.close()


def test_match_chain_is_contiguous_prefix_only(tmp_path):
    st = _store(tmp_path)
    for h in (10, 11, 13):  # 12 never stored: the chain has a hole
        st.store(h, *_page(h))
    assert st.match_chain([10, 11, 12, 13]) == [10, 11]
    assert st.match_chain([12, 13]) == []
    assert st.match_chain([]) == []
    st.close()


def test_boot_scan_adopts_survivors_and_quarantines_torn_tail(tmp_path):
    st = _store(tmp_path)
    for h in (1, 2, 3):
        st.store(h, *_page(h))
    st.close()
    # Power-cut emulation: hash 2's file survives as a torn prefix.
    victim = os.path.join(str(tmp_path), f"{2:016x}.kv")
    blob = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(blob[: len(blob) // 2])
    st2 = _store(tmp_path)
    assert st2.boot_scan() == 2
    assert st2.torn_pages == 1
    assert st2.adopted == 2
    # The torn file moved aside for forensics, never adoptable.
    assert os.path.exists(
        os.path.join(str(tmp_path), "quarantine", f"{2:016x}.kv")
    )
    # The hole detaches the chain suffix exactly like a G2 miss would.
    assert st2.match_chain([1, 2, 3]) == [1]
    k, _v = st2.fetch(1)
    np.testing.assert_array_equal(k, _page(1)[0])
    assert st2.ledger_check() == []
    st2.close()


def test_boot_scan_sweeps_tmp_orphans(tmp_path):
    st = _store(tmp_path)
    st.store(1, *_page(1))
    st.close()
    # A crash between the tmp write and the rename leaves an orphan the
    # publish rename never blessed: boot must clear, never adopt, it.
    orphan = os.path.join(str(tmp_path), f"{9:016x}.kv.tmp")
    with open(orphan, "wb") as f:
        f.write(b"half a page")
    st2 = _store(tmp_path)
    assert st2.boot_scan() == 1
    assert not os.path.exists(orphan)
    assert 9 not in st2
    st2.close()


def test_boot_scan_tolerates_torn_manifest_tail(tmp_path):
    st = _store(tmp_path)
    for h in (1, 2):
        st.store(h, *_page(h))
    st.close()
    with open(os.path.join(str(tmp_path), "manifest.jsonl"), "a") as f:
        f.write('{"op": "put", "ha')  # crash mid-append
    st2 = _store(tmp_path)
    assert st2.boot_scan() == 2
    assert st2.manifest_torn == 1
    assert st2.match_chain([1, 2]) == [1, 2]
    st2.close()


def test_bitflip_fetch_quarantines_and_bars_readmission(tmp_path):
    chaos = StorageChaos(7).bitflip_read(times=1)
    st = _store(tmp_path, chaos=chaos)
    st.store(5, *_page(5))
    # The flipped read must checksum-fail: no garbage bytes served.
    assert st.fetch(5) is None
    assert st.checksum_failures == 1
    assert st.quarantined == 1 and st.misses == 1
    assert chaos.injected == ["store_read:bitflip"]
    names = os.listdir(os.path.join(str(tmp_path), "quarantine"))
    assert names == [f"{5:016x}.kv"]
    # A proven-corrupt key is terminal: no re-store, no re-match.
    assert not st.store(5, *_page(5))
    assert st.match_chain([5]) == []
    assert st.fetch(5) is None
    assert st.ledger_check() == []
    # Nor does a later boot re-adopt it (the journal remembers).
    st.close()
    st2 = _store(tmp_path)
    assert st2.boot_scan() == 0
    assert st2.match_chain([5]) == []
    st2.close()


def test_enospc_degrades_to_noop_writes(tmp_path):
    chaos = StorageChaos(3).enospc(times=1)
    st = _store(tmp_path, chaos=chaos)
    assert not st.store(1, *_page(1))
    assert st.degraded and st.store_errors == 1
    # Degradation is sticky: later (fault-free) writes stay no-ops and
    # reads stay safe misses — G2-only behavior, never an exception.
    assert not st.store(2, *_page(2))
    assert st.fetch(1) is None
    assert st.resident == 0
    assert st.ledger_check() == []
    st.close()


def test_uncreatable_root_degrades_at_construction(tmp_path):
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("a file where the store dir should go")
    st = _store(blocker / "g3")
    assert st.degraded
    assert not st.store(1, *_page(1))
    assert st.fetch(1) is None
    assert st.boot_scan() == 0
    assert st.match_chain([1]) == []
    assert st.ledger_check() == []
    st.close()


def test_ledger_conservation_across_all_transitions(tmp_path):
    chaos = StorageChaos(11).bitflip_read(times=1)
    st = _store(tmp_path, cap=2, chaos=chaos)
    for h in (1, 2, 3):  # one capacity eviction
        st.store(h, *_page(h))
    st.fetch(2)  # bit-flipped: quarantine
    st.store(2, *_page(2))  # refused
    st.store(4, *_page(4))  # readmit up to capacity
    led = st.ledger()
    assert led["violations"] == []
    assert led["resident"] == (
        led["adopted"] + led["stores"] - led["evictions"] - led["quarantined"]
    )
    assert led["resident"] == 2 and led["quarantined"] == 1
    st.close()
    # Survivors adopt; the ledger equation holds in the next process.
    st2 = _store(tmp_path, cap=2)
    st2.boot_scan()
    assert st2.adopted == 2
    assert st2.ledger()["violations"] == []
    st2.close()


# --------------------------------------------------------- wire checksums
def test_wire_checksum_rejects_corrupt_frame():
    from dynamo_exp_tpu.disagg import transfer

    pages = [_page(1), _page(2)]
    header, payload = transfer.encode_pages(pages)
    assert len(header["sums"]) == 2
    out = transfer.decode_pages(header, payload)
    np.testing.assert_array_equal(out[1][1], pages[1][1])
    before = transfer.wire_checksum_failures()
    corrupt = bytearray(payload)
    corrupt[len(corrupt) // 2] ^= 0x10
    with pytest.raises(ValueError, match="wire checksum"):
        transfer.decode_pages(header, bytes(corrupt))
    assert transfer.wire_checksum_failures() == before + 1
    # Older senders omit sums: their frames still decode (no checksum).
    legacy = dict(header)
    del legacy["sums"]
    assert len(transfer.decode_pages(legacy, payload)) == 2


def test_page_file_header_crc_covers_meta_and_payload(tmp_path):
    st = _store(tmp_path)
    st.store(1, *_page(1))
    blob = open(os.path.join(str(tmp_path), f"{1:016x}.kv"), "rb").read()
    _magic, crc, _h, _meta_len = _HEADER.unpack_from(blob)
    assert crc == zlib.crc32(blob[_HEADER.size:])
    st.close()


# -------------------------------------------------------------- engine e2e
def make_engine(store_dir=None, pages=20, host_pages=6, slots=2,
                store_pages=256, chaos=None, **kw):
    cfg = EngineConfig(
        model=TINY,
        max_decode_slots=slots,
        page_size=PS,
        num_pages=pages,
        max_model_len=256,
        eos_token_ids=[],
        prefix_sharing=True,
        host_cache_pages=host_pages,
        kv_dtype="float32",  # bit-exact across G2/G3 round trips
        kv_store_dir="" if store_dir is None else str(store_dir),
        kv_store_pages=store_pages,
        kv_store_chaos=chaos,
        **kw,
    )
    return TPUEngine(cfg, mesh=single_device_mesh(), seed=0)


async def run_req(engine, prompt, n=6, seed=None, temperature=None):
    b = BackendInput(token_ids=list(prompt))
    b.stop_conditions.max_tokens = n
    b.stop_conditions.ignore_eos = True
    if seed is not None:
        b.sampling_options.seed = seed
    if temperature is not None:
        b.sampling_options.temperature = temperature
    stream = await engine.generate(b.to_dict())
    tokens = []
    async for item in stream:
        tokens.extend(item.get("token_ids", []))
    return tokens


def _hard_kill(engine):
    """Crash emulation: the loop thread dies with NO graceful teardown
    — no offload flush, no G2→G3 snapshot drain, no manifest seal or
    close. Whatever the demotion write-through already committed is all
    the next boot gets, exactly like a power cut. Helper threads are
    reaped so the test process stays clean."""
    engine._running = False
    engine._wake.set()
    if engine._watchdog is not None:
        engine._watchdog.stop()
        engine._watchdog = None
    if engine._thread is not None:
        engine._thread.join(timeout=30)
        assert not engine._thread.is_alive()
        engine._thread = None
    if engine.copy_stream is not None:
        engine.copy_stream.stop()
        engine.copy_stream = None


def _convo_and_churn(seed, n_churn=2):
    rs = np.random.RandomState(seed)
    convo = [int(x) for x in rs.randint(3, 200, size=3 * PS)]
    # Near-pool-sized churn prompts: each one forces the parked
    # conversation pages G1→G2, and the small G2 overflows the oldest
    # of them through the demotion write-through into G3.
    churn = [
        [int(x) for x in rs.randint(3, 200, size=16 * PS)]
        for _ in range(n_churn)
    ]
    return convo, churn


async def _seed_store(engine, convo, churn, n=6, **sampling):
    """Run the conversation, then enough distinct churn that its parked
    pages fall G1→G2 and overflow the small G2 into the G3 writer."""
    want = await run_req(engine, convo, n=n, **sampling)
    for p in churn:
        await run_req(engine, p, n=2)
    return want


async def test_restart_resume_identity_greedy(tmp_path):
    """The headline: kill an engine mid-conversation (no graceful
    drain), boot a fresh process over the same store directory, and the
    returning conversation re-attaches its persisted prefix — proven by
    the persist hit counter — emitting exactly the pre-crash tokens."""
    convo, churn = _convo_and_churn(7)
    eng = make_engine(store_dir=tmp_path / "g3")
    eng.start()
    want = await _seed_store(eng, convo, churn)
    # The demotion write-through put pages on disk BEFORE the crash.
    assert eng.g3_store.resident > 0
    _hard_kill(eng)
    eng2 = make_engine(store_dir=tmp_path / "g3")
    assert eng2.g3_store.adopted > 0  # boot_scan rebuilt the survivors
    eng2.start()
    try:
        got = await run_req(eng2, convo, n=6)
        assert got == want
        assert eng2.kv.prefix_hits["persist"] > 0
        m = eng2.metrics()
        assert m["kv_prefix_hits_persist"] > 0
        assert m["kv_store_promotes"] > 0
        assert m["kv_store_checksum_failures"] == 0
        assert m["kv_store_degraded"] == 0
        audit = eng2.kv_audit()
        assert audit["ok"], audit["violations"]
        assert audit["g3"]["violations"] == []
    finally:
        eng2.stop()


async def test_restart_resume_identity_seeded(tmp_path):
    convo, churn = _convo_and_churn(11)
    sampling = dict(seed=123, temperature=0.8)
    eng = make_engine(store_dir=tmp_path / "g3")
    eng.start()
    want = await _seed_store(eng, convo, churn, n=8, **sampling)
    assert eng.g3_store.resident > 0
    _hard_kill(eng)
    eng2 = make_engine(store_dir=tmp_path / "g3")
    eng2.start()
    try:
        # Counter-based sampling keys on absolute position: restored
        # pages shift nothing, the sampled stream replays exactly.
        got = await run_req(eng2, convo, n=8, **sampling)
        assert got == want
        assert eng2.kv.prefix_hits["persist"] > 0
    finally:
        eng2.stop()


async def test_stop_drains_pending_g2_demotions_through_g3(tmp_path):
    """Graceful shutdown: the whole warm G2 set demotes through the G3
    writer and the sealed manifest covers it — the next boot adopts a
    cache as warm as the stopped process was. A wedged loop skips the
    drain (teardown must never race a live loop thread)."""
    convo, churn = _convo_and_churn(13, n_churn=3)
    # Roomy G2: churn parks pages in the host pool without overflowing
    # them into G3 — stop() is what must flush them.
    eng = make_engine(store_dir=tmp_path / "g3", host_pages=64)
    eng.start()
    await _seed_store(eng, convo, churn)
    assert eng.host_pool.resident > 0
    warm_g2 = eng.host_pool.resident
    before = eng.g3_store.stores

    # A wedged loop thread must make stop() skip the teardown flush
    # entirely: no drain, no seal, state untouched for the live thread.
    class _Wedged:
        def join(self, timeout=None):
            pass

        def is_alive(self):
            return True

    real = eng._thread
    eng._thread = _Wedged()
    eng.stop()
    assert eng.g3_store.stores == before
    assert eng.copy_stream is not None  # teardown never ran

    # The real loop exited on stop()'s _running=False; a second stop
    # with the joinable thread restored performs the full drain.
    eng._thread = real
    eng.stop()
    assert eng.g3_store.stores >= before + warm_g2
    drained = eng.g3_store.resident
    # The sealed manifest makes every drained page adoptable.
    eng2 = make_engine(store_dir=tmp_path / "g3")
    assert eng2.g3_store.adopted == drained
    eng2.start()
    eng2.stop()


# ---------------------------------------------- seeded storage-fault family
@pytest.mark.chaos
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
async def test_corrupt_page_containment_token_identical(tmp_path, seed):
    """Bit-flipped G3 pages at restart: not one token decodes from the
    corrupt bytes — the fetch checksum-fails, quarantines, and the
    block journal-re-prefills token-identically to the fault-free
    restart. No hang, auditor green throughout."""
    convo, churn = _convo_and_churn(seed)
    eng = make_engine(store_dir=tmp_path / "g3")
    eng.start()
    want = await _seed_store(eng, convo, churn)
    eng.stop()  # graceful: full drain + sealed manifest

    chaos = StorageChaos(seed).bitflip_read(times=2)
    eng2 = make_engine(store_dir=tmp_path / "g3", chaos=chaos)
    assert eng2.g3_store.adopted > 0
    eng2.start()
    try:
        got = await run_req(eng2, convo, n=6)
        assert got == want  # identical despite the flipped pages
        m = eng2.metrics()
        assert m["kv_store_checksum_failures"] > 0
        assert m["kv_store_quarantined"] > 0
        # The shortened restore re-prefilled instead of serving garbage.
        assert chaos.injected
        audit = eng2.kv_audit()
        assert audit["ok"], audit["violations"]
        assert audit["g3"]["violations"] == []
    finally:
        eng2.stop()


@pytest.mark.chaos
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
async def test_torn_write_containment_token_identical(tmp_path, seed):
    """Torn demotion writes (power-cut shape): the next boot's scan
    quarantines the torn files, adopts the survivors, and the returning
    conversation is token-identical — the holes just re-prefill."""
    convo, churn = _convo_and_churn(seed + 1)
    chaos = StorageChaos(seed).torn_write(times=2)
    eng = make_engine(store_dir=tmp_path / "g3", chaos=chaos)
    eng.start()
    want = await _seed_store(eng, convo, churn)
    assert chaos.injected  # the torn writes actually fired
    _hard_kill(eng)
    eng2 = make_engine(store_dir=tmp_path / "g3")
    assert eng2.g3_store.torn_pages > 0
    eng2.start()
    try:
        got = await run_req(eng2, convo, n=6)
        assert got == want
        assert eng2.metrics()["kv_store_torn"] > 0
    finally:
        eng2.stop()


@pytest.mark.chaos
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
async def test_enospc_and_slow_reads_degrade_never_stall(tmp_path, seed):
    """ENOSPC mid-demotion flips the store to G2-only no-ops; slow
    fetches slow restores — neither wedges the engine loop, and both
    runs complete token-identically to their own re-runs."""
    convo, churn = _convo_and_churn(seed + 2, n_churn=4)
    chaos = StorageChaos(seed).enospc(times=1)
    eng = make_engine(store_dir=tmp_path / "g3", chaos=chaos)
    eng.start()
    try:
        want = await _seed_store(eng, convo, churn)
        assert eng.g3_store.degraded
        m = eng.metrics()
        assert m["kv_store_degraded"] == 1 and m["kv_store_errors"] >= 1
        # G2-only behavior: the same prompt still replays identically.
        assert await run_req(eng, convo, n=6) == want
    finally:
        t0 = time.monotonic()
        eng.stop()  # drain over a degraded store: bounded no-op
        assert time.monotonic() - t0 < 30.0

    # Slow store reads: seeded delays on the restore path, zero hangs.
    slow_dir = tmp_path / "slow"
    eng3 = make_engine(store_dir=slow_dir)
    eng3.start()
    want3 = await _seed_store(eng3, convo, churn)
    eng3.stop()
    eng4 = make_engine(
        store_dir=slow_dir, chaos=StorageChaos(seed).delay_read(0.02, times=3)
    )
    eng4.start()
    try:
        assert await run_req(eng4, convo, n=6) == want3
    finally:
        eng4.stop()


@pytest.mark.chaos
async def test_missing_store_dir_runs_g2_only(tmp_path):
    """The fifth family member: an uncreatable store directory degrades
    at construction — the engine serves normally as G2-only."""
    blocker = tmp_path / "blocker"
    blocker.write_text("not a directory")
    eng = make_engine(store_dir=blocker / "g3")
    assert eng.g3_store.degraded
    eng.start()
    try:
        convo, _ = _convo_and_churn(5, n_churn=0)
        first = await run_req(eng, convo, n=6)
        assert await run_req(eng, convo, n=6) == first
        assert eng.metrics()["kv_store_degraded"] == 1
    finally:
        eng.stop()


# -------------------------------------------------------------------- sim
@pytest.mark.sim
def test_sim_restart_drill_restores_g3_prefix_deterministically():
    """The modeled restart drill: churn evicts the conversation's pages
    into the instance's G3 dict, the drill hard-restarts the host (the
    respawn inherits the dict — same disk), and the returning group
    re-attaches restored pages. Bit-identical across same-seed runs."""
    from dynamo_exp_tpu.sim import ClusterSim, SimConfig
    from dynamo_exp_tpu.sim.workload import SimRequest

    reqs = [
        SimRequest(index=0, arrival_s=0.0, prompt_len=80, max_tokens=4,
                   prefix_group=0, prefix_len=64),
        SimRequest(index=1, arrival_s=5.0, prompt_len=500, max_tokens=4),
        SimRequest(index=2, arrival_s=50.0, prompt_len=80, max_tokens=4,
                   prefix_group=0, prefix_len=64),
    ]

    def run(g3_pages):
        sim = ClusterSim(
            SimConfig(
                seed=9, slots_per_instance=4, pages_per_instance=32,
                page_size=16, initial_instances=1, max_inflight=16,
                prefix_sharing=True, g3_pages_per_instance=g3_pages,
                restart_at_s=30.0, provision_s=5.0,
            ),
            list(reqs),
        )
        rep = sim.run()
        return sim.event_log, rep

    log1, rep1 = run(64)
    assert rep1.restarts == 1
    assert rep1.completed == 3 and rep1.errors == 0
    assert rep1.g3_restored_pages >= 4
    log2, rep2 = run(64)
    assert log1 == log2
    assert rep1.to_dict() == rep2.to_dict()
    # Without the durable tier the drill loses the prefix entirely.
    _log0, rep0 = run(0)
    assert rep0.restarts == 1 and rep0.g3_restored_pages == 0
