"""Ring attention + sequence-parallel prefill on the 8-device CPU mesh.

A capability the reference lacks (SURVEY.md §5): context parallelism.
Oracle = the dense/paged single-device paths already tested elsewhere.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dynamo_exp_tpu.parallel import shard_map
from jax.sharding import PartitionSpec as P

from dynamo_exp_tpu.models import TINY, forward, init_kv_cache, init_params
from dynamo_exp_tpu.models.llama import forward_ring_prefill
from dynamo_exp_tpu.ops.attention import dense_causal_attention
from dynamo_exp_tpu.ops.ring_attention import ring_attention
from dynamo_exp_tpu.parallel import build_mesh

SP = 8


def ring_mesh():
    return build_mesh(sp=SP)


def run_ring(mesh, q, k, v, q_pos, kv_pos):
    seq4 = P(None, "sp", None, None)
    seq2 = P(None, "sp")
    fn = shard_map(
        partial(ring_attention, axis_name="sp", axis_size=SP),
        mesh=mesh,
        in_specs=(seq4, seq4, seq4, seq2, seq2),
        out_specs=seq4,
        check_vma=False,
    )
    return fn(q, k, v, q_pos, kv_pos)


def test_ring_matches_dense_gqa():
    B, T, H, Hkv, D = 2, 64, 4, 2, 16
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, T, Hkv, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, T, Hkv, D), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    want = dense_causal_attention(q, k, v)
    got = run_ring(ring_mesh(), q, k, v, pos, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_ring_handles_padding_rows():
    """Trailing padding (pos = -1) must produce zeros and not perturb
    valid rows."""
    B, T, H, Hkv, D = 1, 32, 2, 2, 8
    valid = 19  # not a multiple of the shard size
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, T, Hkv, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, T, Hkv, D), jnp.float32)
    pos_np = np.full((B, T), -1, np.int32)
    pos_np[:, :valid] = np.arange(valid)
    pos = jnp.asarray(pos_np)
    got = np.asarray(run_ring(ring_mesh(), q, k, v, pos, pos))
    want = np.asarray(dense_causal_attention(q[:, :valid], k[:, :valid], v[:, :valid]))
    np.testing.assert_allclose(got[:, :valid], want, atol=1e-5)
    np.testing.assert_array_equal(got[:, valid:], 0.0)


def test_ring_prefill_matches_paged_forward():
    """Full-model sequence-parallel prefill == the paged single-device
    forward, logits and KV both."""
    import dataclasses

    cfg = dataclasses.replace(TINY, dtype="float32")
    T = 64
    ps = 8
    params = init_params(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(2)
    tokens = jnp.asarray(rs.randint(3, cfg.vocab_size, size=(1, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))

    # Oracle: paged forward on pages 0..T/ps-1.
    k0, v0 = init_kv_cache(cfg, num_pages=T // ps, page_size=ps)
    table = jnp.arange(T // ps, dtype=jnp.int32)[None, :]
    want_logits, want_k, want_v = forward(
        params, cfg, tokens, positions, table, k0, v0
    )

    mesh = ring_mesh()
    got_logits, got_k, got_v = forward_ring_prefill(
        params, cfg, tokens, positions, mesh
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits), atol=2e-3, rtol=1e-3
    )
    # Ring K/V is [L, B, T, Hkv, D]; oracle pool is [L, P, ps, Hkv*D]
    # (the fused-lane layout).
    L, Pn, _, fused = np.asarray(want_k).shape
    np.testing.assert_allclose(
        np.asarray(got_k).reshape(L, Pn, ps, fused), np.asarray(want_k), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(got_v).reshape(L, Pn, ps, fused), np.asarray(want_v), atol=1e-5
    )


def test_ring_prefill_composes_with_tp():
    """sp=4 × tp=2: tp-sharded projections + ring over the sequence must
    reproduce the replicated single-device forward (the round-4 fix for
    the 'params replicated inside the sp path' limitation)."""
    import dataclasses

    cfg = dataclasses.replace(TINY, dtype="float32")  # exact split-K sums
    ps = 8
    T = 64
    params = init_params(jax.random.PRNGKey(0), cfg)
    rs = np.random.RandomState(4)
    tokens = jnp.asarray(rs.randint(3, cfg.vocab_size, size=(1, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (1, T))

    k0, v0 = init_kv_cache(cfg, num_pages=T // ps, page_size=ps)
    table = jnp.arange(T // ps, dtype=jnp.int32)[None, :]
    want_logits, want_k, _ = forward(params, cfg, tokens, positions, table, k0, v0)

    mesh = build_mesh(sp=4, tp=2)
    got_logits, got_k, got_v = forward_ring_prefill(
        params, cfg, tokens, positions, mesh
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits), atol=2e-4, rtol=1e-4
    )
    # K/V: ring [L, B, T, Hkv, D] vs fused-lane pool [L, P, ps, Hkv*D].
    L, Pn, _, fused = np.asarray(want_k).shape
    np.testing.assert_allclose(
        np.asarray(got_k).reshape(L, Pn, ps, fused),
        np.asarray(want_k),
        atol=1e-5,
    )


def test_ring_prefill_rejects_indivisible_seq():
    cfg = TINY
    mesh = ring_mesh()
    params = init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="divisible"):
        forward_ring_prefill(
            params,
            cfg,
            jnp.zeros((1, 30), jnp.int32),
            jnp.zeros((1, 30), jnp.int32),
            mesh,
        )
