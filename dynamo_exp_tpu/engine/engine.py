"""The TPU execution engine: continuous batching on a paged KV cache.

This replaces the reference's wrapped GPU engines (vLLM/sglang/TRT-LLM —
``/root/reference/lib/engines/``, SURVEY.md §2.3/§2.9) with an in-process
JAX engine:

- **Two small families of compiled programs** drive everything: decode
  *windows* (``lax.scan`` over ``decode_window`` steps with sampled
  tokens fed back on-device, keyed by row bucket / attention impl /
  page bucket / sampler variant — one host sync per window, which is
  what survives a high-latency host↔device link) and batched chunked
  prefill (keyed by row bucket × token bucket × page bucket). Static
  shapes, no recompiles in steady state; KV pools are donated so XLA
  updates them in place in HBM.
- **Decode cost tracks occupancy, not the slot envelope**
  (docs/engine_perf.md): ACTIVE rows are compacted into the smallest
  row bucket and partitioned greedy-vs-sampler; stop detection (EOS /
  stop ids / budget) runs on-device inside the window so finished rows
  park at position -1 instead of writing garbage KV; KV pages move in
  batched multi-page gathers/scatters (one dispatch per sequence or
  eviction burst); and in steady state the next window launches from
  the previous window's device carry before the host syncs, so emit
  processing overlaps device compute.
- **The host loop is the scheduler** (reference's "hard part #3",
  SURVEY.md §7): stop flags, admissions, page allocation, and KV event
  emission all happen between steps on the loop thread — never inside a
  compiled region. The host's ``check_stop`` stays authoritative; the
  on-device stop is an optimization, not the source of truth.
- **Prefix caching is free at the attention level**: reused pages are
  already resident; prefill just starts its positions after the cached
  prefix (write-then-gather attention reads them like any other page).
- **Tensor parallelism** comes from param/cache shardings over the
  engine's mesh; XLA inserts the ICI collectives.

The engine exposes the same ``AsyncEngine`` seam the rest of the stack
uses (``BackendInput`` dict in → ``LLMEngineOutput`` dict stream out), so
the preprocessor/backend/router layers are engine-agnostic, matching the
reference's ``ExecutionContext`` contract (``lib/llm/src/backend.rs:60``).
"""

from __future__ import annotations

import asyncio
import logging
import queue
import random
import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import AsyncIterator, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..models.config import ModelConfig
from ..models.llama import (
    Params,
    forward,
    init_kv_cache,
    init_params,
    kv_cache_shardings,
    param_shardings,
)
from ..ops.sampling import (
    apply_penalties,
    sample_tokens_seeded,
    spec_accept_length,
    spec_verify_tokens,
    stop_token_hit,
    token_logprobs,
)
from ..parallel.mesh import build_mesh
from ..protocols.common import BackendInput, FinishReason, LLMEngineOutput
from ..runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream
from ..telemetry import current_trace, get_telemetry
from ..telemetry.dispatch import DispatchProfiler
from ..telemetry.flight import (
    FlightRecorder,
    Watchdog,
    default_dump_path,
    register_dumper,
    unregister_dumper,
)
from .config import EngineConfig
from .kv_manager import KvEvent, KvPageManager
from .offload import CopyStream, HostKvPool
from .scheduler import RemoteKv, Scheduler, SeqState, Sequence

log = logging.getLogger(__name__)


@dataclass
class _PendingDecode:
    """One dispatched decode window the host has not yet consumed.

    Holds the device-side results (``ys``) plus the final scan carry
    (``tokens_dev``/``positions_dev``) — the exact inputs of the next
    window over the same rows, so a chained dispatch can launch window
    N+1 straight from device state while the host still owns window N's
    sync (see ``TPUEngine._dispatch_chained``)."""

    ys: tuple  # [K, rows] sampled tokens (+ logprob arrays when want_lp)
    tokens_dev: object  # final carry: next window's input tokens [rows]
    positions_dev: object  # final carry: next window's positions [rows]
    stepped: list  # [(Sequence, n_valid, row)]
    rows: int  # row bucket (array batch dim)
    full_sampler: bool
    want_lp: bool
    solo: bool  # only decode dispatch of its iteration -> chainable
    # True when some row could hit its page/model-length cap inside this
    # window (cap < wpos + K at dispatch). Its device carry position
    # flips to -1 at the cap, but the host RESUMES such a row after
    # allocating pages rather than finishing it — so a chained window
    # would feed the dead carry and emit garbage. Chaining requires this
    # to be False; stop/budget deaths are safe (the host finishes those
    # rows at consume and skips them in the successor).
    capacity_capped: bool
    stop_tokens: object  # np [rows, S], reused verbatim by a chain
    # (seeds, temp, top_k, top_p, f, p, r) np arrays, reused by a chain.
    sampler_args: tuple | None = None
    slot_map: object | None = None  # np [rows] (sampler variants only)
    # Dispatch-profiler stamp (monotonic, taken right after the dispatch
    # call returned): the consume's existing host sync closes the pair.
    dispatched_at: float = 0.0


@dataclass
class _PendingPrefill:
    """One dispatched prefill chunk awaiting its host sync."""

    ys: tuple
    completed: list  # [(row, Sequence)] rows whose prompt finished
    want_lp: bool
    dispatched_at: float = 0.0  # dispatch-profiler stamp


@dataclass
class _PendingSpec:
    """One dispatched speculative verify pass (docs/speculative.md).

    Always consumed in the same loop iteration it was dispatched —
    speculation re-plans drafts from the freshly accepted tokens every
    round, so there is nothing to chain (spec rows break the device-to-
    device decode chain exactly like capacity-capped rows do)."""

    ys: tuple  # targets [rows, T], n_emit [rows] (+ lp arrays when want_lp)
    stepped: list  # [(Sequence, n_drafts, row)]
    full_sampler: bool
    want_lp: bool
    dispatched_at: float = 0.0  # dispatch-profiler stamp


class TPUEngine(AsyncEngine):
    """Continuous-batching paged-KV engine on a TPU mesh."""

    def __init__(
        self,
        cfg: EngineConfig,
        params: Params | None = None,
        mesh: Mesh | None = None,
        kv_event_cb: Callable[[KvEvent], None] | None = None,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.mesh = mesh or build_mesh(tp=cfg.tp, sp=cfg.sp)
        mcfg = cfg.model

        def sharding(spec):
            return NamedSharding(self.mesh, spec)

        if params is None:
            params = init_params(jax.random.PRNGKey(seed), mcfg)
        self.params = jax.device_put(
            params,
            jax.tree.map(
                sharding,
                param_shardings(mcfg),
                is_leaf=lambda x: isinstance(x, P),
            ),
        )
        kspec, vspec = kv_cache_shardings()
        k, v = init_kv_cache(
            mcfg, cfg.num_pages, cfg.page_size, dtype=cfg.kv_dtype_jnp
        )
        self.k_cache = jax.device_put(k, sharding(kspec))
        self.v_cache = jax.device_put(v, sharding(vspec))

        self.host_pool: HostKvPool | None = None
        self.copy_stream: CopyStream | None = None
        on_evict = None
        if cfg.host_cache_pages > 0:
            page_shape = (
                mcfg.num_layers,
                cfg.page_size,
                mcfg.num_kv_heads * mcfg.head_dim_,
            )
            self.host_pool = HostKvPool(
                cfg.host_cache_pages, page_shape, cfg.kv_dtype_jnp
            )

            # The CopyStream (a live thread) is created by start(), so a
            # constructed-but-never-started engine owns no threads.
            def on_evict(pid: int, seq_hash: int) -> None:
                # Coalesce: eviction bursts (a big allocation reclaiming
                # many parked pages) buffer here and flush as ONE batched
                # gather right before the next compute dispatch — stream
                # order still protects the pages from the forward that
                # overwrites them, but the burst costs one dispatch + one
                # host sync instead of one per page.
                self._pending_offloads.append((pid, seq_hash))

        self.kv = KvPageManager(
            cfg.num_pages,
            cfg.page_size,
            event_cb=kv_event_cb if cfg.enable_kv_events else None,
            host_pool=self.host_pool,
            on_evict=on_evict,
            sharing=cfg.prefix_sharing,
        )
        # Observability (docs/observability.md): per-dispatch profiler
        # (host gap vs in-flight, compile attribution — pure timestamps
        # at the loop's existing sync points) and the flight recorder
        # ring the watchdog/SIGUSR1/crash paths dump.
        self.profiler = (
            DispatchProfiler(get_telemetry()) if cfg.profile_dispatches else None
        )
        self.flight = (
            FlightRecorder(cfg.flight_capacity) if cfg.flight_events else None
        )
        self.sched = Scheduler(cfg, self.kv, flight=self.flight)
        if self.profiler is not None:
            self.sched.span_attrs = self._decode_span_attrs

        # Multi-page movement kernels, shared by the G2 offload tier and
        # the disaggregation KV handoff (gather → wire / wire → inject).
        # ``pids`` is a page_move_bucket_for-padded [n] vector, so a whole
        # sequence (or eviction burst) moves in ONE dispatch; jit's own
        # cache keys the O(log Pmax) bucket shapes. Scatter pads repeat
        # the last (pid, page) pair — duplicate indices with identical
        # updates are deterministic.
        self._gather_pages = jax.jit(
            lambda k, v, pids: (k[:, pids], v[:, pids])
        )
        self._inject_pages = jax.jit(
            lambda k, v, pids, hk, hv: (
                k.at[:, pids].set(hk),
                v.at[:, pids].set(hv),
            ),
            donate_argnums=(0, 1),
        )
        # Copy-on-write page copy (docs/prefix_sharing.md): device-to-
        # device duplicate of one shared page before its first divergent
        # write. Indices ride as traced device scalars, so every COW
        # shares ONE compiled variant.
        self._cow_pages = jax.jit(
            lambda k, v, src, dst: (
                k.at[:, dst].set(k[:, src]),
                v.at[:, dst].set(v[:, src]),
            ),
            donate_argnums=(0, 1),
        )
        # Evictions buffered by on_evict until the next compute dispatch.
        self._pending_offloads: list[tuple[int, int]] = []

        B, V = cfg.max_decode_slots, mcfg.vocab_size
        # Penalty bookkeeping, indexed by slot. Row B is a scratch row:
        # compacted decode windows gather counts through a slot map whose
        # padding rows point here, so pad scatters never touch a live
        # slot's counts.
        self._counts = jnp.zeros((B + 1, V), jnp.int32)
        # Sampling is counter-based per sequence: every draw is keyed by
        # (sequence seed, absolute token position) — see
        # ops/sampling.sample_tokens_seeded. Requests without an explicit
        # seed get one drawn here at submission; a frontend that journals
        # for failover replay pins the seed request-side instead.
        self._seed_rng = random.Random(seed + 1)
        self._attn_impl, self._attn_interpret = self._resolve_attn()
        # Compiled-variant caches. Decode windows are keyed by
        # (row bucket, attention impl, static page bound — None on the
        # Pallas path, which reads true lengths — full-vs-greedy sampler,
        # and want_lp); prefill by (row bucket, token bucket, page bound).
        self._decode_fns: dict[tuple, Callable] = {}
        self._prefill_fns: dict[tuple[int, int, int], Callable] = {}
        # Speculative verify variants, keyed by (row bucket, draft
        # bucket, page bound, full-vs-greedy sampler, want_lp).
        self._spec_fns: dict[tuple, Callable] = {}
        # Host-side speculation state (drafter + per-row adaptive
        # controller); None = speculation off.
        self._spec = None
        if cfg.spec_mode != "off":
            from ..spec import SpecManager

            self._spec = SpecManager(cfg)
        # Fresh penalty row for a slot: zero it, then count the first
        # sampled token so penalties see every generated token.
        self._init_row = jax.jit(
            lambda c, i, t: c.at[i].set(0).at[i, t].add(1),
            donate_argnums=(0,),
        )

        self._submit_q: queue.Queue[Sequence] = queue.Queue()
        self._wake = threading.Event()
        self._running = False
        self._thread: threading.Thread | None = None
        self.steps = 0  # decode step counter (metrics)
        self._last_gauge_pub = 0.0  # telemetry gauge throttle
        self._last_reap = 0.0  # waiting-deque reap throttle
        # Watchdog progress: bumped once per loop iteration that did
        # real work (dispatch/consume/admit). Frozen counter + queued
        # work past the grace = dump the flight ring.
        self._progress_mark = 0
        self._watchdog: Watchdog | None = None
        self._flight_handle: int | None = None
        # Dispatch stamp of the last page-move gather (engine-loop
        # local; the caller's sync consumes it in the same call chain).
        self._last_move_t = 0.0
        # Chained decode: the dispatched-but-unconsumed window (if any).
        self._inflight: _PendingDecode | None = None
        # Occupancy/movement counters (mirrored to /metrics counters and
        # surfaced by metrics() for bench.py's occupancy sweep).
        self.wasted_steps = 0  # window steps computed past a row's stop
        self.kv_page_moves = 0  # pages moved by batched gather/scatter
        self.kv_move_dispatches = 0  # batched-move dispatches issued
        self.preempted = 0  # sequences preempted under KV pressure
        # Speculative decoding counters (docs/speculative.md): proposed
        # draft tokens, the prefix the verify pass accepted, tokens
        # actually emitted, and verify dispatches issued — acceptance
        # rate and tokens-per-dispatch derive from these (mirrored to
        # /metrics and bench.py --spec-sweep).
        self.spec_dispatches = 0  # batched verify dispatches (device)
        self.spec_row_dispatches = 0  # row participations (per-row basis)
        self.spec_draft_tokens = 0
        self.spec_accepted_tokens = 0
        self.spec_emitted_tokens = 0
        # KV handoff leases: confirmations arrive from asyncio threads
        # (the prefill worker's delivery ack) but the page manager is
        # single-writer — queue them for the loop thread, which also
        # runs the expiry reaper each iteration.
        self._lease_confirm_q: queue.Queue[str] = queue.Queue()
        # Prefix pin requests (disagg suffix-only transfer): the decode
        # router asks "how much of this prompt do you already hold?" and
        # pins the answer under a lease. Served on the loop thread (the
        # manager's single writer); results travel back via futures.
        self._pin_q: queue.Queue[tuple] = queue.Queue()
        # Telemetry counter snapshot (prefix sharing): the prometheus
        # prefix-hit mirror advances by delta at gauge-publish time (the
        # page manager itself is telemetry-free; COW has its own event-
        # site counter in _resolve_shared_tail).
        self._pub_prefix_hits = {"shared": 0, "restore": 0, "miss": 0}

    # ----------------------------------------------------------- compiled fns
    def _resolve_attn(self) -> tuple[str, bool]:
        """Pick the decode attention implementation. ``auto`` resolves to
        the ragged Pallas kernel only when the mesh actually sits on TPU
        (or ``pallas_interpret`` forces interpreter mode for CPU tests);
        anywhere else the length-bounded XLA gather is the correct
        choice. Layouts Mosaic can't tile (``pallas_supported``) fall
        back to XLA rather than fail at compile time on the first
        decode."""
        from ..ops.paged_decode import pallas_supported

        cfg = self.cfg
        impl = cfg.attention_impl
        interpret = cfg.pallas_interpret
        if impl == "auto":
            platform = self.mesh.devices.flat[0].platform
            impl = "pallas" if (platform == "tpu" or interpret) else "xla"
        mcfg = cfg.model
        if impl == "pallas" and (
            mcfg.sliding_window is not None
            or mcfg.attn_logit_softcap is not None
            or mcfg.query_pre_attn_scalar is not None
        ):
            # forward() would silently refuse the kernel for these
            # configs (window mask / softcap / scale live on the XLA
            # path); resolve xla HERE so attn_pages keeps bounding the
            # gather — otherwise decode would run the XLA path with an
            # unbounded Pmax-wide page table.
            impl = "xla"
        if impl == "pallas" and not interpret:
            tp = self.mesh.shape.get("tp", 1)
            if not pallas_supported(
                cfg.page_size,
                cfg.model.num_kv_heads // tp,
                cfg.model.head_dim_,
                cfg.kv_dtype_jnp,
            ):
                log.warning(
                    "KV layout (ps=%d, Hkv=%d/tp=%d, D=%d, %s) is not "
                    "Mosaic-tileable; decode falls back to the XLA path",
                    cfg.page_size,
                    cfg.model.num_kv_heads,
                    tp,
                    cfg.model.head_dim_,
                    cfg.kv_dtype,
                )
                impl = "xla"
        return impl, interpret

    def _decode_fn(
        self,
        rows: int,
        attn_pages: int | None,
        full_sampler: bool,
        want_lp: bool,
    ):
        """One compiled decode *window*: ``decode_window`` steps run
        on-device under ``lax.scan`` with sampled tokens fed straight
        back — the host syncs once per window instead of once per token,
        which is what makes decode throughput survive a high-latency
        host↔device link.

        ``rows`` is the compacted batch dim (decode_rows_bucket_for of
        the ACTIVE row count), NOT max_decode_slots: at occupancy 1 the
        window computes 1 row, so decode FLOPs and HBM traffic track
        true load. ``full_sampler=False`` is the greedy fast path (no
        penalties, no top-k/p machinery, no RNG, no counts traffic)
        used for the greedy partition of the batch — one creative
        request no longer drags every greedy row through the sampler.

        Stop detection runs on-device: each row carries a padded stop
        set plus EOS/budget step gates, and a row that stops flips its
        position to -1 mid-window — no garbage KV writes, no page
        overrun past EOS — which makes large ``decode_window`` values
        profitable instead of a tail-latency tax. The host's check_stop
        stays authoritative for everything it can see.

        The final scan carry (tokens, positions) is returned so the next
        window over the same rows can be dispatched device-to-device
        (chained) before the host syncs on this one.

        Even when the Pallas kernel is available, short contexts take
        the XLA gather: below ~1k tokens of page bucket the gather's
        HBM traffic is trivial and the kernel's serial per-row DMA grid
        costs more than it saves. The kernel wins where it matters —
        long contexts, where gather traffic scales with rows*bucket
        while the kernel's scales with the true total context."""
        impl, interpret, mesh = self._attn_impl, self._attn_interpret, self.mesh
        if (
            impl == "pallas"
            and self.cfg.attention_impl == "auto"  # explicit pallas is honored
            and attn_pages * self.cfg.page_size <= 1024
        ):
            impl = "xla"
        pages = None if impl == "pallas" else attn_pages
        key = (rows, impl, pages, full_sampler, want_lp)
        fn = self._decode_fns.get(key)
        if fn is not None:
            return fn
        mcfg = self.cfg.model
        K = self.cfg.decode_window

        def run_forward(params, tokens, positions, page_table, k, v):
            logits, k, v = forward(
                params, mcfg, tokens[:, None], positions[:, None],
                page_table, k, v, attn_pages=pages, attn_impl=impl,
                mesh=mesh, interpret=interpret,
            )
            return logits[:, 0], k, v  # [rows, V]

        def advance(positions, max_pos, next_tok, stop_set, eos_gate,
                    budget_gate, t, active):
            # A row leaves the window (position -1, writes dropped) when
            # it hits its page/model-length capacity, samples a token
            # from its stop set past its min-tokens gate, or exhausts
            # its remaining max_tokens budget.
            done = (
                stop_token_hit(next_tok, stop_set) & (t >= eos_gate)
            ) | (t >= budget_gate)
            return jnp.where(
                active & ~done & (positions < max_pos), positions + 1, -1
            )

        if full_sampler:

            @partial(jax.jit, donate_argnums=(1, 2, 8))
            def decode_window(params, k, v, tokens, positions, max_pos,
                              page_table, seeds, counts_all, slot_map, temp,
                              top_k, top_p, freq_pen, pres_pen, rep_pen,
                              stop_set, eos_gate, budget_gate):
                # Compaction: penalty rows live slot-indexed in the
                # [B+1, V] pool; gather the stepped rows in, scatter
                # back out (pad rows map to the scratch row B).
                counts0 = counts_all[slot_map]

                def step(carry, t):
                    tokens, positions, k, v, counts = carry
                    logits, k, v = run_forward(
                        params, tokens, positions, page_table, k, v
                    )
                    shaped = apply_penalties(
                        logits, counts, freq_pen, pres_pen, rep_pen
                    )
                    # Counter-based draw keyed by (seed, fed position):
                    # deterministic replay across instances/windows, the
                    # property resumable streams rebuild state from.
                    next_tok = sample_tokens_seeded(
                        shaped, seeds, positions, temp, top_k, top_p
                    )
                    # OpenAI logprobs: of the MODEL distribution (raw
                    # logits, pre-penalty/temperature), chosen + top-k.
                    # Compiled only into the want_lp variant — the common
                    # no-logprobs workload pays neither the full-vocab
                    # log_softmax nor the extra per-window host transfer.
                    if want_lp:
                        lp, top_ids, top_lp = token_logprobs(logits, next_tok)
                    active = positions >= 0
                    counts = counts.at[
                        jnp.arange(counts.shape[0]), next_tok
                    ].add(active.astype(jnp.int32))
                    tokens = jnp.where(active, next_tok, tokens)
                    positions = advance(
                        positions, max_pos, next_tok, stop_set, eos_gate,
                        budget_gate, t, active,
                    )
                    ys = (
                        (next_tok, lp, top_ids, top_lp)
                        if want_lp
                        else (next_tok,)
                    )
                    return (tokens, positions, k, v, counts), ys

                (tokens, positions, k, v, counts), ys = jax.lax.scan(
                    step, (tokens, positions, k, v, counts0),
                    jnp.arange(K),
                )
                counts_all = counts_all.at[slot_map].set(counts)
                # ys: toks [K,rows] (+ lp [K,rows], top_ids/top_lp
                # [K,rows,N] when want_lp).
                return ys, k, v, counts_all, tokens, positions

        else:

            @partial(jax.jit, donate_argnums=(1, 2))
            def decode_window(params, k, v, tokens, positions, max_pos,
                              page_table, stop_set, eos_gate, budget_gate):
                def step(carry, t):
                    tokens, positions, k, v = carry
                    logits, k, v = run_forward(
                        params, tokens, positions, page_table, k, v
                    )
                    next_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    if want_lp:
                        lp, top_ids, top_lp = token_logprobs(logits, next_tok)
                    active = positions >= 0
                    tokens = jnp.where(active, next_tok, tokens)
                    positions = advance(
                        positions, max_pos, next_tok, stop_set, eos_gate,
                        budget_gate, t, active,
                    )
                    ys = (
                        (next_tok, lp, top_ids, top_lp)
                        if want_lp
                        else (next_tok,)
                    )
                    return (tokens, positions, k, v), ys

                (tokens, positions, k, v), ys = jax.lax.scan(
                    step, (tokens, positions, k, v), jnp.arange(K)
                )
                return ys, k, v, tokens, positions

        self._decode_fns[key] = decode_window
        return decode_window

    def _prefill_fn(
        self, rows: int, bucket: int, attn_pages: int, want_lp: bool
    ):
        key = (rows, bucket, attn_pages, want_lp)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        mcfg = self.cfg.model

        @partial(jax.jit, donate_argnums=(1, 2))
        def prefill_step(params, k, v, tokens, positions, page_table, seeds,
                         last_idx, temp, top_k, top_p):
            logits, k, v = forward(
                params, mcfg, tokens, positions, page_table, k, v,
                attn_pages=attn_pages, last_positions=last_idx,
            )
            # Key the first-token draw by the absolute position of the
            # prompt's last token — identical to the draw a decode window
            # would make feeding that token, so prefill chunking and
            # continuation re-prefills replay the same sample.
            last_pos = jnp.take_along_axis(
                positions, last_idx[:, None], axis=1
            )[:, 0]
            toks = sample_tokens_seeded(
                logits[:, 0], seeds, last_pos, temp, top_k, top_p
            )
            if want_lp:
                lp, top_ids, top_lp = token_logprobs(logits[:, 0], toks)
                return (toks, lp, top_ids, top_lp), k, v
            return (toks,), k, v

        self._prefill_fns[key] = prefill_step
        return prefill_step

    def _spec_fn(
        self,
        rows: int,
        k_bucket: int,
        attn_pages: int,
        full_sampler: bool,
        want_lp: bool,
    ):
        """One compiled speculative *verify* pass (docs/speculative.md):
        the row's last confirmed token plus up to ``k_bucket`` draft
        tokens ride through the target model as a T = k_bucket + 1 wide
        chunked-prefill-shaped dispatch (always the XLA paged path —
        ``forward`` only takes the Pallas decode kernel at T == 1), and
        the target's counter-keyed token at every absolute position
        comes back in the same dispatch.

        Because each draw is keyed by (seed, fed position) — the same
        key the step-by-step decode window would use — the accepted
        prefix plus the first correction token is *exactly* the token
        sequence the non-speculative engine would have emitted. The
        greedy variant is a plain per-position argmax; the full-sampler
        variant threads penalty counts through a scan with rejected
        positions masked out of the counts (ops/sampling.
        spec_verify_tokens), so the penalty state rewinds with the KV.

        KV for positions past the accepted prefix is teacher-forced
        garbage, but attention masks strictly by query position and the
        host rewinds ``wpos`` to the accepted length, so the next
        dispatch overwrites the first garbage slot and never attends
        past its own position — no garbage KV survives."""
        key = (rows, k_bucket, attn_pages, full_sampler, want_lp)
        fn = self._spec_fns.get(key)
        if fn is not None:
            return fn
        mcfg = self.cfg.model
        pages = attn_pages

        def pack_ys(logits, targets, n_emit):
            if not want_lp:
                return (targets, n_emit)
            V = logits.shape[-1]
            lp, tid, tlp = token_logprobs(
                logits.reshape(-1, V), targets.reshape(-1)
            )
            B, T = targets.shape
            return (
                targets,
                n_emit,
                lp.reshape(B, T),
                tid.reshape(B, T, -1),
                tlp.reshape(B, T, -1),
            )

        if full_sampler:

            @partial(jax.jit, donate_argnums=(1, 2, 7))
            def spec_verify(params, k, v, tokens, positions, page_table,
                            n_drafts, counts_all, slot_map, seeds, temp,
                            top_k, top_p, freq_pen, pres_pen, rep_pen):
                logits, k, v = forward(
                    params, mcfg, tokens, positions, page_table, k, v,
                    attn_pages=pages,
                )
                counts0 = counts_all[slot_map]
                targets, n_emit, counts = spec_verify_tokens(
                    logits, tokens[:, 1:], n_drafts, seeds, positions,
                    temp, top_k, top_p, counts0, freq_pen, pres_pen,
                    rep_pen,
                )
                counts_all = counts_all.at[slot_map].set(counts)
                return pack_ys(logits, targets, n_emit), k, v, counts_all

        else:

            @partial(jax.jit, donate_argnums=(1, 2))
            def spec_verify(params, k, v, tokens, positions, page_table,
                            n_drafts):
                logits, k, v = forward(
                    params, mcfg, tokens, positions, page_table, k, v,
                    attn_pages=pages,
                )
                targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                n_emit = spec_accept_length(targets, tokens[:, 1:], n_drafts)
                return pack_ys(logits, targets, n_emit), k, v

        self._spec_fns[key] = spec_verify
        return spec_verify

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._running:
            return
        if self._thread is not None:
            if self._thread.is_alive():
                # A wedged previous loop survived a timed-out stop(): a
                # second loop thread would race it over scheduler/page/
                # inflight state the moment the old one unwedges.
                log.error(
                    "previous engine loop thread is still alive; refusing "
                    "to start a second loop"
                )
                return
            # The wedged loop later unwedged and exited, but the timed-out
            # stop() skipped its teardown: drop the stale in-flight window
            # and buffered evictions — the pages they reference belong to
            # the previous run.
            self._thread = None
            self._inflight = None  # dynlint: thread-ownership(loop thread joined before teardown flush)
            self._pending_offloads.clear()  # dynlint: thread-ownership(loop thread joined before teardown flush)
        if self.host_pool is not None and self.copy_stream is None:
            # stop() tears the copy stream down; a restarted engine needs
            # a live one before the first eviction fires on_evict.
            self.copy_stream = CopyStream(self.host_pool)
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="tpu-engine-loop", daemon=True
        )
        self._thread.start()
        if self.flight is not None:
            self._flight_handle = register_dumper(self._dump_flight)
            if self.cfg.watchdog_stall_s > 0 and self._watchdog is None:
                self._watchdog = Watchdog(
                    self.cfg.watchdog_stall_s,
                    progress=lambda: self._progress_mark,
                    has_work=lambda: (
                        self.sched.has_work() or not self._submit_q.empty()
                    ),
                    dump_fn=self._dump_flight,
                )
                self._watchdog.start()

    def stop(self) -> None:
        self._running = False
        self._wake.set()
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self._flight_handle is not None:
            unregister_dumper(self._flight_handle)
            self._flight_handle = None
        if self._thread:
            # The teardown below mutates loop-owned state, so it may only
            # run once the loop thread has actually exited. A wedged loop
            # (e.g. stuck in a pathological compile) keeps its state: a
            # concurrent flush would race whatever it is still doing.
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                log.error(
                    "engine loop did not exit within 30s; skipping "
                    "teardown flush to avoid racing the live loop thread"
                )
                return
            self._thread = None
        self._inflight = None  # dynlint: thread-ownership(loop thread joined before teardown flush)
        # Prefix-pin requests queued after the loop's last service pass
        # must not hang their callers (disagg routing awaits them).
        self._drain_pin_q()
        if self.copy_stream is not None:
            # Flush evictions the dead loop buffered, then drain
            # (bounded) so a graceful drain doesn't silently discard
            # queued host-tier offloads — every committed page is a
            # recompute the next instance of this prefix never pays.
            self._flush_offloads()
            self.copy_stream.drain()
            self.copy_stream.stop()
            self.copy_stream = None

    # ------------------------------------------------------------ AsyncEngine
    async def generate(
        self,
        request: dict | BackendInput,
        context: AsyncEngineContext | None = None,
        remote_kv: RemoteKv | None = None,
    ) -> ResponseStream[dict]:
        if not self._running:
            self.start()
            if not self._running:
                # start() refused (wedged previous loop): submitting
                # would enqueue work nothing will ever consume.
                raise RuntimeError(
                    "engine is not running (previous loop thread is "
                    "still alive after a timed-out stop)"
                )
        ctx = context or AsyncEngineContext()
        binput = (
            request
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        loop = asyncio.get_running_loop()
        out_q: asyncio.Queue = asyncio.Queue()

        def emit(
            tokens: list[int],
            reason: FinishReason | None,
            logprobs=None,  # (lps: list[float], tops: list[dict]) | None
        ) -> None:
            loop.call_soon_threadsafe(
                out_q.put_nowait, (tokens, reason, logprobs)
            )

        seq = Sequence(
            request_id=ctx.id,
            prompt=list(binput.token_ids),
            stop=binput,
            emit=emit,
            is_cancelled=lambda: ctx.is_stopped,
            remote_kv=remote_kv,
            trace=current_trace(),
            submitted_at=time.time(),
            sample_seed=self._effective_seed(binput),
            priority=binput.priority,
            deadline_unix=ctx.deadline or 0.0,
        )
        self._submit_q.put(seq)
        self._wake.set()
        prompt_tokens = len(binput.token_ids)

        async def _gen() -> AsyncIterator[dict]:
            completion = 0
            while True:
                tokens, reason, logprobs = await out_q.get()
                if tokens:
                    completion += len(tokens)
                    yield LLMEngineOutput(
                        token_ids=tokens,
                        logprobs=logprobs[0] if logprobs else None,
                        top_logprobs=logprobs[1] if logprobs else None,
                    ).to_dict()
                if reason is not None:
                    yield LLMEngineOutput(
                        finish_reason=reason,
                        prompt_tokens=prompt_tokens,
                        completion_tokens=completion,
                    ).to_dict()
                    return

        return ResponseStream(_gen(), ctx)

    def _effective_seed(self, binput: BackendInput) -> int:
        """The request's pinned sampling seed, or one drawn now. With a
        pinned seed (journaling frontends always pin one for sampled
        requests), the whole token stream is a pure function of
        (weights, prompt, sampling params) — replayable anywhere."""
        s = binput.sampling_options.seed
        return int(s) if s is not None else self._seed_rng.getrandbits(31)

    async def prefill_extract(
        self,
        request: dict | BackendInput,
        context: AsyncEngineContext | None = None,
        skip_pages: int = 0,
    ) -> tuple[int, list, str]:
        """Run prefill only; hand back (first_token, kv_pages, lease_id).

        This is the prefill-worker side of disaggregation: the prompt's
        KV pages (host-bounced numpy, one (k, v) pair per page) travel to
        the decode worker, which injects them via ``generate(...,
        remote_kv=...)``. ``skip_pages`` is the decode side's pinned
        resident prefix (suffix-only transfer, docs/prefix_sharing.md):
        those pages are neither gathered nor shipped — the full prompt
        is still prefilled locally (so this worker's pool prefix-hits
        repeats), but the wire and the extract gather carry only the
        unshared suffix. Until the caller confirms delivery
        (:meth:`confirm_kv_lease`) — or the lease TTL passes and the
        reaper reclaims them — the shipped device pages stay pinned, so
        a decode worker that dies between extract and inject can never
        strand HBM.
        """
        if not self._running:
            self.start()
            if not self._running:
                raise RuntimeError(
                    "engine is not running (previous loop thread is "
                    "still alive after a timed-out stop)"
                )
        ctx = context or AsyncEngineContext()
        binput = (
            request.model_copy(deep=True)  # never mutate the caller's object
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        binput.stop_conditions.max_tokens = 1  # prefill produces one token
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def extract_cb(token: int, pages: list, lease_id: str) -> None:
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result((token, pages, lease_id))
            )

        def emit(
            tokens: list[int], reason: FinishReason | None, logprobs=None
        ) -> None:
            if reason in (FinishReason.ERROR, FinishReason.CANCELLED):
                loop.call_soon_threadsafe(
                    lambda: fut.done()
                    or fut.set_exception(RuntimeError(f"prefill failed: {reason}"))
                )

        seq = Sequence(
            request_id=ctx.id,
            prompt=list(binput.token_ids),
            stop=binput,
            emit=emit,
            is_cancelled=lambda: ctx.is_stopped,
            extract_cb=extract_cb,
            extract_skip=max(int(skip_pages), 0),
            trace=current_trace(),
            submitted_at=time.time(),
            sample_seed=self._effective_seed(binput),
            priority=binput.priority,
            deadline_unix=ctx.deadline or 0.0,
        )
        self._submit_q.put(seq)
        self._wake.set()
        return await fut

    def confirm_kv_lease(self, lease_id: str) -> None:
        """Delivery ack for an extract lease (thread-safe: queues the
        confirm for the engine loop, the page manager's single writer)."""
        self._lease_confirm_q.put(lease_id)
        self._wake.set()

    async def pin_prefix(self, token_ids: list[int]) -> tuple[int, str | None]:
        """How many full prompt pages this engine already holds — pinned.

        The disagg decode router calls this before offloading a prefill:
        the answer becomes the request's ``skip_blocks`` (the prefill
        worker ships only the unshared suffix), and the returned lease
        keeps the matched pages resident until admission re-references
        them (the engine confirms the lease at inject; the reaper is the
        TTL backstop). Thread-safe: the match + pin run on the engine
        loop, the page manager's single writer. Returns ``(0, None)``
        when the engine is not running, sharing is disabled, or it
        holds nothing."""
        if not self._running or not self.kv.sharing:
            # A prefix_sharing=False engine never re-attaches at
            # admission, so a skip would discard the whole transfer.
            return (0, None)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pin_q.put((list(token_ids), loop, fut))
        self._wake.set()
        if not self._running and not fut.done():
            # stop() drained the queue before our put landed: nothing
            # will ever service this entry — resolve it ourselves (the
            # done() guards make a racing resolver a no-op).
            fut.set_result((0, None))
        return await fut

    def _service_pins(self) -> None:
        """Engine-loop side of :meth:`pin_prefix`: match the resident
        *filled* prefix (bytes that exist on device now) and pin it."""
        while True:
            try:
                tokens, loop, fut = self._pin_q.get_nowait()
            except queue.Empty:
                return
            pages, _ = self.kv.match_prefix(tokens, require_filled=True)
            lease = (
                self.kv.grant_lease(pages, self.cfg.kv_lease_ttl_s)
                if pages
                else None
            )
            result = (len(pages), lease)

            def resolve(f=fut, r=result, lease=lease):
                # Runs on the caller's event loop. A future already done
                # (cancelled request) can never hand the lease back —
                # release the pin instead of waiting out its TTL.
                if f.done():
                    if lease is not None:
                        self.confirm_kv_lease(lease)
                else:
                    f.set_result(r)

            try:
                loop.call_soon_threadsafe(resolve)
            except RuntimeError:  # caller's loop closed: release the pin
                if lease is not None:
                    self.kv.confirm_lease(lease)

    # -------------------------------------------------------------- the loop
    def _loop(self) -> None:
        """One iteration = admit everything admissible, dispatch at most
        one batched prefill chunk, then one decode window — so decode
        interleaves between the chunks of long prompts instead of
        stalling behind them (scheduler v2 policy, ``scheduler.py``
        module docstring).

        The host pipelines against the device instead of blocking on
        ``np.asarray`` right after each dispatch: a decode window is
        left *in flight* and consumed one iteration later, and in steady
        state (no arrivals, no prefill, single partition) window N+1 is
        dispatched straight from window N's on-device carry BEFORE the
        host syncs on window N — so emits, stop checks, page
        registration, and admissions for window N overlap window N+1's
        device time. All scheduler mutation that could free pages still
        happens only when no unconsumed window could write to them."""
        try:
            while self._running:
                # Lease bookkeeping first: confirmations queued by the
                # prefill worker's delivery ack, then the expiry reaper
                # (orphaned handoffs whose decode instance died). Both
                # mutate the page manager, so they run here — its single
                # writer — every iteration, busy or idle.
                self._service_leases()
                self._service_pins()
                if self._inflight is not None:
                    # Steady state: launch the next window device-to-
                    # device, then consume the previous one while the
                    # new one executes.
                    nxt = (
                        self._dispatch_chained(self._inflight)
                        if self._can_chain()
                        else None
                    )
                    prev, self._inflight = self._inflight, nxt
                    self._consume_decode(prev)
                    self._maybe_publish_gauges()
                    self._progress_mark += 1  # consumed a window
                    if self._inflight is not None:
                        continue
                    # Chain broken (arrivals / prefill / stop / dry
                    # pool): fall through to the full scheduling path.
                    if self.flight is not None:
                        self.flight.record("chain_break")
                if not self.sched.has_work() and self._submit_q.empty():
                    # Flush buffered evictions before idling (the host
                    # tier must see them even with no next dispatch) and
                    # publish on the idle path too: the gauges must decay
                    # to zero after the last request finishes, not freeze
                    # on the final busy-loop snapshot.
                    self._flush_offloads()
                    self._maybe_publish_gauges()
                    if self.profiler is not None:
                        # Genuinely idle: wait time must never read as
                        # host gap on the next dispatch.
                        self.profiler.mark_idle()
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                self._drain_submissions()
                self._poll_cancellations()
                # Reap dead work anywhere in the waiting deque before it
                # can waste a prefill or hold an admission slot. The full
                # O(queue-depth) scan is throttled: the loop can spin at
                # kHz when the pool is dry, and admit_next's head check
                # still prevents a wasted prefill between scans.
                now_m = time.monotonic()
                if now_m - self._last_reap >= 0.02:
                    self._last_reap = now_m
                    self.sched.reap_waiting()
                # KV pressure: no window is in flight here (the chain
                # broke above or never existed), so releasing a victim's
                # pages cannot race a device write.
                self._maybe_preempt()
                if not self._kv_pressure():
                    while (admitted := self.sched.admit_next()) is not None:
                        self._on_admitted(admitted)
                self._maybe_publish_gauges()
                progressed = False
                prefilling = [
                    s
                    for s in self.sched.slots
                    if s is not None and s.state is SeqState.PREFILL
                ]
                # Partition the snapshot BEFORE injecting: injection
                # clears remote_kv and promotes the sequence to ACTIVE,
                # so filtering afterwards would re-prefill it. Sequences
                # attached to shared pages another sequence is still
                # filling sit out until those fills are dispatched
                # (fill_ready also claims orphans left by dead fillers)
                # — device stream order then makes their reads safe.
                ready = [s for s in prefilling if self.sched.fill_ready(s)]
                batch = [s for s in ready if s.remote_kv is None]
                for seq in ready:
                    if seq.remote_kv is not None:
                        self._run_remote_inject(seq)
                        progressed = True
                pending_prefill = None
                if batch:
                    pending_prefill = self._dispatch_prefill_chunk(
                        batch[: self.cfg.prefill_batch]
                    )
                    progressed = True
                # Decode dispatches BEFORE the prefill sync: the window
                # executes behind the prefill on the device stream while
                # the host consumes prefill completions.
                pendings, spec_pendings = self._dispatch_decode()
                progressed = progressed or bool(pendings) or bool(spec_pendings)
                if pending_prefill is not None:
                    self._consume_prefill(pending_prefill)
                # Verify passes consume in the same iteration: the next
                # round's drafts are proposed from the tokens they just
                # confirmed, so there is nothing to overlap.
                for sp in spec_pendings:
                    self._consume_spec(sp)
                if (
                    len(pendings) == 1
                    and pendings[0].solo
                    and self.cfg.chained_decode
                ):
                    self._inflight = pendings[0]  # consumed next iteration
                else:
                    for p in pendings:
                        self._consume_decode(p)
                if progressed:
                    self._progress_mark += 1
                else:
                    # Pool dry / everything stalled: yield briefly. No
                    # progress bump — this is exactly the state the
                    # watchdog must see as frozen.
                    if self.profiler is not None:
                        self.profiler.mark_idle()
                    self._wake.wait(timeout=0.001)
                    self._wake.clear()
        except Exception:  # engine death must not hang clients
            log.exception("engine loop crashed; failing in-flight requests")
            self._dump_flight("crash")
            self._running = False
            self._inflight = None
            self._fail_all()
            raise

    def _on_admitted(self, seq: Sequence) -> None:
        """Close the request's queue-wait stage (submission -> slot +
        pages bound). Runs on the engine loop thread with the trace
        captured at submission."""
        now = time.time()
        seq.admitted_at = now
        if self.flight is not None:
            self.flight.record(
                "admit",
                req=seq.request_id,
                slot=seq.slot,
                prompt=len(seq.prompt),
                cached=seq.cached_len,
                priority=seq.priority,
            )
        tel = get_telemetry()
        if seq.submitted_at:
            tel.queue_wait.observe(max(now - seq.submitted_at, 0.0))
            tel.emit_stage(
                "queue_wait",
                seq.submitted_at,
                now,
                seq.trace,
                prompt_tokens=len(seq.prompt),
            )

    # --------------------------------------------------- flight / profiling
    def _decode_span_attrs(self) -> dict:
        """Dispatch-profiler attrs for the decode span (scheduler.finish
        emits it): median in-flight/host-gap per decode window plus the
        window size, so sim/fit.py can fit per-dispatch service times
        straight from span files."""
        if self.profiler is None:
            return {}
        return self.profiler.span_attrs(
            "decode", decode_window=self.cfg.decode_window
        )

    def _flight_snapshot(self) -> dict:
        """Best-effort scheduler/slot/page state for a flight dump. May
        run on the watchdog thread while the loop is wedged — read-only,
        and a torn read beats no dump."""
        try:
            slots = []
            for i, s in enumerate(self.sched.slots):
                if s is None:
                    continue
                slots.append(
                    {
                        "slot": i,
                        "req": s.request_id,
                        "state": s.state.value,
                        "generated": s.generated,
                        "pages": len(s.page_ids),
                        "stalled": bool(s.stalled_since),
                        "preemptions": s.preemptions,
                    }
                )
            return {
                "slots": slots,
                "waiting": len(self.sched.waiting),
                "submitted_unqueued": self._submit_q.qsize(),
                "pages_active": self.kv.active_pages,
                "pages_total": self.kv.num_pages,
                "inflight_window": self._inflight is not None,
                "progress_mark": self._progress_mark,
            }
        except Exception:  # noqa: BLE001 - snapshot is best-effort
            log.exception("flight snapshot failed")
            return {}

    def _dump_flight(self, reason: str) -> None:
        """Dump the flight ring + snapshot (watchdog stall, SIGUSR1 via
        the process registry, or engine-loop crash)."""
        if self.flight is None:
            return
        path = self.cfg.flight_dump_path or default_dump_path()
        self.flight.dump(path, reason, snapshot=self._flight_snapshot())

    def _maybe_publish_gauges(self) -> None:
        """Mirror engine gauges into the telemetry registry at most
        ~2x/second — the loop can spin thousands of times faster."""
        now = time.monotonic()
        if now - self._last_gauge_pub >= 0.5:
            self._last_gauge_pub = now
            tel = get_telemetry()
            tel.publish_engine_gauges(self.metrics())
            # Prefix-hit counters advance by delta (the page manager is
            # telemetry-free; its in-object counters are authoritative).
            for kind, total in self.kv.prefix_hits.items():
                delta = total - self._pub_prefix_hits[kind]
                if delta:
                    tel.kv_prefix_hits.labels(kind).inc(delta)
                    self._pub_prefix_hits[kind] = total

    def _service_leases(self) -> None:
        """Engine-loop-thread lease upkeep: apply queued delivery
        confirmations, then reap expired handoff leases so a decode
        instance dying between extract and inject returns the pinned
        pages within one lease period."""
        while True:
            try:
                self.kv.confirm_lease(self._lease_confirm_q.get_nowait())
                if self.flight is not None:
                    self.flight.record("lease_confirm")
            except queue.Empty:
                break
        if self.kv.active_leases:
            reclaimed = self.kv.reap_expired()
            if reclaimed:
                if self.flight is not None:
                    self.flight.record("lease_reap", pages=reclaimed)
                get_telemetry().kv_lease_reclaims.inc(reclaimed)
                log.warning(
                    "reaped %d KV pages from expired handoff leases "
                    "(decode side never confirmed delivery)", reclaimed,
                )

    def _drain_submissions(self) -> None:
        while True:
            try:
                self.sched.submit(self._submit_q.get_nowait())
            except queue.Empty:
                return

    # ------------------------------------------------------- overload control
    def _kv_pressure(self) -> bool:
        """True while any bound row is hard-stalled (cannot feed its
        next token because the pool is dry). Admission pauses under this
        condition: a newcomer's allocation would take the very pages the
        stalled rows are waiting for — including pages a preemption just
        parked for them."""
        return any(
            s is not None and s.stalled_since for s in self.sched.slots
        )

    def _maybe_preempt(self) -> None:
        """KV-pressure preemption (docs/fault_tolerance.md "Overload
        protection"): once a row has been hard-stalled past the grace
        period, evict the lowest-priority / youngest ACTIVE sequence —
        its pages park (reusable, offload-tier write-back on eviction)
        and it requeues as a deterministic continuation of itself, so
        its stream resumes token-identically once pressure clears.
        Bounded per request by ``max_preemptions_per_seq``; each event
        lands in the trace timeline as a ``preemption`` span."""
        grace = self.cfg.preempt_stall_grace_s
        if grace < 0:
            return
        now = time.time()
        if not any(
            s is not None
            and s.stalled_since
            and now - s.stalled_since >= grace
            for s in self.sched.slots
        ):
            return
        if self.sched.active_count <= 1 and not self.sched.waiting:
            return  # nothing to yield the freed pages to
        victim = self.sched.preemption_victim(self.cfg.max_preemptions_per_seq)
        if victim is None:
            return
        t0 = victim.stalled_since or now
        freed = len(victim.page_ids)
        generated = victim.generated
        self.sched.preempt(victim)
        self.preempted += 1
        tel = get_telemetry()
        tel.preemptions.labels("kv_pressure").inc()
        tel.emit_stage(
            "preemption",
            t0,
            now,
            victim.trace,
            generated_tokens=generated,
            freed_pages=freed,
            priority=victim.priority,
            preemption=victim.preemptions,
        )
        log.warning(
            "KV pressure: preempted request %s (priority=%d, %d tokens "
            "generated, %d pages freed, preemption %d/%d); resuming as a "
            "deterministic continuation",
            victim.request_id, victim.priority, generated, freed,
            victim.preemptions, self.cfg.max_preemptions_per_seq,
        )

    def _poll_cancellations(self) -> None:
        now = time.time()
        for s in list(self.sched.slots):
            if s is None:
                continue
            if s.is_cancelled():
                self.sched.finish(s, FinishReason.CANCELLED)
            elif s.deadline_unix and now >= s.deadline_unix:
                # Bound rows honor deadlines too — without this, a row
                # stalled at its preemption bound with an expired
                # deadline would hold its slot and pages until the
                # client disconnected.
                get_telemetry().deadline_exceeded.labels("decode").inc()
                self.sched.finish(s, FinishReason.ERROR)

    def _fail_all(self) -> None:
        for s in list(self.sched.slots):
            if s is not None:
                self.sched.finish(s, FinishReason.ERROR)
        while self.sched.waiting:
            s = self.sched.waiting.popleft()
            s.emit([], FinishReason.ERROR)
        while not self._submit_q.empty():
            try:
                self._submit_q.get_nowait().emit([], FinishReason.ERROR)
            except queue.Empty:
                break
        self._drain_pin_q()

    def _drain_pin_q(self) -> None:
        """Resolve every queued prefix-pin request with the no-coverage
        answer — callers await these futures unboundedly, so shutdown
        and crash paths must never strand one."""
        while not self._pin_q.empty():
            try:
                _tokens, loop, fut = self._pin_q.get_nowait()
            except queue.Empty:
                break
            try:
                loop.call_soon_threadsafe(
                    lambda f=fut: f.done() or f.set_result((0, None))
                )
            except RuntimeError:
                pass

    # ----------------------------------------------------- batched page moves
    def _gather_page_batch(self, pids: list[int], kind: str = "kv_move"):
        """ONE compiled multi-page gather: device [L, bucket, ps, HkvD]
        K/V pairs covering ``pids`` (bucket-padded with the last pid; the
        caller slices back to len(pids)). One dispatch per call — a
        3k-ISL extract moves ~190 pages here instead of 190 dispatches
        and 190 host syncs. ``kind`` labels the dispatch for the
        profiler (``kv_move`` for extract, ``offload`` for eviction
        bursts); the stamp parks in ``_last_move_t`` for whichever
        existing sync consumes it."""
        bucket = self.cfg.page_move_bucket_for(len(pids))
        padded = np.full(bucket, pids[-1], np.int32)
        padded[: len(pids)] = pids
        prof = self.profiler
        if prof is not None:
            fresh = prof.first_variant("gather", bucket)
            t0 = prof.begin(kind)
        k_b, v_b = self._gather_pages(
            self.k_cache, self.v_cache, jnp.asarray(padded)
        )
        if prof is not None:
            self._last_move_t = prof.end(kind, t0, fresh)  # dynlint: thread-ownership(loop thread joined before teardown flush)
        if self.flight is not None:
            self.flight.record("dispatch", dispatch=kind, pages=len(pids))
        self.kv_move_dispatches += 1  # dynlint: thread-ownership(loop thread joined before teardown flush)
        self.kv_page_moves += len(pids)  # dynlint: thread-ownership(loop thread joined before teardown flush)
        return k_b, v_b

    def _inject_page_batch(self, pids: list[int], k_pages, v_pages, op: str):
        """ONE compiled multi-page scatter of host pages (list of
        [L, ps, HkvD] numpy arrays) into device pages ``pids``. Pads by
        repeating the last (pid, page) pair — duplicate scatter indices
        with identical updates are deterministic. Buffered evictions
        flush first so a page being overwritten was gathered for the
        host tier before this scatter lands."""
        self._flush_offloads()
        bucket = self.cfg.page_move_bucket_for(len(pids))
        pad = bucket - len(pids)
        pid_arr = np.full(bucket, pids[-1], np.int32)
        pid_arr[: len(pids)] = pids
        hk = np.stack(list(k_pages) + [k_pages[-1]] * pad, axis=1)
        hv = np.stack(list(v_pages) + [v_pages[-1]] * pad, axis=1)
        prof = self.profiler
        if prof is not None:
            # A scatter is never host-synced (dispatch order protects
            # it), so only the dispatch leg is profiled — adding a sync
            # here is exactly what the profiler must never do.
            fresh = prof.first_variant("scatter", bucket)
            t0 = prof.begin("kv_move")
        self.k_cache, self.v_cache = self._inject_pages(
            self.k_cache,
            self.v_cache,
            jnp.asarray(pid_arr),
            jnp.asarray(hk),
            jnp.asarray(hv),
        )
        if prof is not None:
            prof.end("kv_move", t0, fresh)
        if self.flight is not None:
            self.flight.record(
                "dispatch", dispatch="kv_move", op=op, pages=len(pids)
            )
        self.kv_move_dispatches += 1
        self.kv_page_moves += len(pids)
        get_telemetry().kv_page_moves.labels(op).inc(len(pids))

    def _flush_offloads(self) -> None:
        """Batch-gather every eviction buffered since the last compute
        dispatch and hand the burst to the CopyStream as one item.
        Called right before anything that could overwrite the evicted
        pages (decode/prefill/inject dispatches) and on the idle path —
        stream order then guarantees the gather reads the old content."""
        if not self._pending_offloads:
            return
        moved, self._pending_offloads = self._pending_offloads, []  # dynlint: thread-ownership(loop thread joined before teardown flush)
        if self.copy_stream is None:
            return
        k_b, v_b = self._gather_page_batch(
            [pid for pid, _ in moved], kind="offload"
        )
        on_synced = None
        if self.profiler is not None:
            # The CopyStream worker's np.asarray is this dispatch's one
            # host sync; its completion callback is the consume point.
            prof, t_disp = self.profiler, self._last_move_t
            on_synced = lambda: prof.consume("offload", t_disp)  # noqa: E731
        self.copy_stream.offload_batch(
            [h for _, h in moved], k_b, v_b, on_synced=on_synced
        )
        get_telemetry().kv_page_moves.labels("offload").inc(len(moved))

    # ---------------------------------------------------------------- prefill
    def _apply_uploads(self, seq: Sequence) -> None:
        """Re-inject G2 host pages into their fresh device pages before
        the compute that attends over them (dispatch order on the device
        stream makes this safe without explicit sync) — one batched
        scatter per sequence, not one per page."""
        if not seq.pending_uploads:
            return
        upload_pids = [pid for pid, _h, _k, _v in seq.pending_uploads]
        self._inject_page_batch(
            upload_pids,
            [hk for _pid, _h, hk, _v in seq.pending_uploads],
            [hv for _pid, _h, _k, hv in seq.pending_uploads],
            op="upload",
        )
        # Content is on the stream: sharers waiting on these restored
        # pages can dispatch behind it.
        self.kv.mark_filled(upload_pids)
        seq.pending_uploads = []

    @staticmethod
    def _wants_logprobs(seq: Sequence) -> int | None:
        """The request's top_logprobs count (0 = chosen only), or None."""
        return seq.stop.sampling_options.logprobs

    @staticmethod
    def _lp_pack(n_top: int, lps, top_ids, top_lps):
        """Host-side logprob payload for emit: per-token chosen logprob
        plus the top-n alternatives (n sliced from the static TOP_LOGPROBS
        the device computes)."""
        tops = None
        if n_top > 0:
            tops = [
                {int(t): float(l) for t, l in zip(tid[:n_top], tlp[:n_top])}
                for tid, tlp in zip(top_ids, top_lps)
            ]
        return ([float(x) for x in lps], tops)

    def _finish_first_token(
        self, seq: Sequence, token: int, lp_pack=None
    ) -> None:
        """Shared tail of the two admission paths (computed prefill or
        remote-KV injection): record + announce the first sampled token
        and promote the sequence to decode. ``lp_pack`` is None on the
        remote-KV path — the first token was sampled on the prefill
        worker, which doesn't ship its distribution."""
        now = time.time()
        seq.first_token_at = seq.last_emit_at = now
        tel = get_telemetry()
        start = seq.admitted_at or seq.submitted_at or now
        tel.prefill_compute.observe(max(now - start, 0.0))
        tel.emit_stage(
            "prefill",
            start,
            now,
            seq.trace,
            prompt_tokens=len(seq.prompt),
            cached_tokens=seq.cached_len,
            remote=seq.remote_prefilled or None,
            resumed_tokens=seq.stop.resume_offset or None,
            # Dispatch-profiler medians (sim/fit.py reads these).
            **(
                self.profiler.span_attrs("prefill")
                if self.profiler is not None
                else {}
            ),
        )
        seq.state = SeqState.ACTIVE
        self._counts = self._init_row(self._counts, seq.slot, token)
        resumed = seq.stop.resume_offset or 0
        if resumed and self._needs_sampler(seq):
            # Failover continuation with penalties: the re-prefilled tail
            # of token_ids is journaled *completion* tokens — rebuild the
            # penalty counts from it so every post-splice decode draw
            # sees the counts the uninterrupted run would have. (The
            # splice token itself was just sampled by prefill, which
            # reads the raw model distribution — see the documented
            # caveat in docs/fault_tolerance.md.)
            V = self.cfg.model.vocab_size
            vec = np.zeros(V, np.int32)
            tail = np.clip(
                np.asarray(seq.prompt[-resumed:], np.int64),  # dynlint: sync-point(host-list conversion)
                0,
                V - 1,
            )
            np.add.at(vec, tail, 1)
            self._counts = self._counts.at[seq.slot].add(jnp.asarray(vec))
        seq.tokens.append(token)
        seq.generated = 1
        self.sched.register_full_pages(seq)
        if seq.extract_cb is not None:
            pages, lease_id = self._extract_prompt_pages(seq)
            seq.extract_cb(token, pages, lease_id)
        reason = self.sched.check_stop(seq, token)
        seq.emit([token], None, lp_pack)
        if reason is not None:
            self.sched.finish(seq, reason)

    def _extract_prompt_pages(self, seq: Sequence) -> tuple[list, str]:
        """Host-bounce every prompt page (incl. the partial tail) for the
        disaggregation handoff: ONE batched gather dispatch and ONE host
        sync per sequence. Runs on the engine loop thread: the prefill
        worker's job is exactly this transfer. The device pages are
        pinned under a handoff lease (granted here, while the sequence
        still holds its refs) until the caller confirms delivery or the
        reaper reclaims them."""
        ps = self.cfg.page_size
        n_pages = (len(seq.prompt) + ps - 1) // ps
        skip = min(seq.extract_skip, n_pages)
        pids = seq.page_ids[skip:n_pages]
        if not pids:
            return [], ""
        k_b, v_b = self._gather_page_batch(pids)
        k_np, v_np = np.asarray(k_b), np.asarray(v_b)  # dynlint: sync-point(extract gather consume)
        if self.profiler is not None:
            self.profiler.consume("kv_move", self._last_move_t)
        if self.flight is not None:
            self.flight.record(
                "consume", dispatch="kv_move", pages=len(pids)
            )
        get_telemetry().kv_page_moves.labels("extract").inc(len(pids))
        lease_id = self.kv.grant_lease(pids, self.cfg.kv_lease_ttl_s)
        if self.flight is not None:
            self.flight.record(
                "lease_grant", req=seq.request_id, pages=len(pids)
            )
        return [
            (
                np.ascontiguousarray(k_np[:, i]),
                np.ascontiguousarray(v_np[:, i]),
            )
            for i in range(len(pids))
        ], lease_id

    def _run_remote_inject(self, seq: Sequence) -> None:
        """Disaggregated admission: prompt KV was computed by a remote
        prefill worker — inject it (one batched scatter) and go straight
        to decode. Suffix-only transfers (docs/prefix_sharing.md) ship
        ``rk.pages`` starting at prompt page ``rk.skip_pages``; the
        decode-side pin that guaranteed those first pages stayed
        resident is released here."""
        self._apply_uploads(seq)
        ps = self.cfg.page_size
        rk = seq.remote_kv
        if rk.pin_lease:
            # Admission re-referenced the pinned pages (or is about to
            # fall back); either way the routing-time pin has done its
            # job. The sequence's own refs keep the pages alive now.
            self.kv.confirm_lease(rk.pin_lease)
            rk.pin_lease = None
        n_pages = (len(seq.prompt) + ps - 1) // ps
        if rk.skip_pages and seq.cached_len // ps < rk.skip_pages:
            # The local prefix the transfer skipped is no longer fully
            # resident (pin reaped under an extreme queue wait): the
            # received suffix is useless without it. Fall back to local
            # prefill — the sequence simply stays in PREFILL.
            log.warning(
                "request %s: suffix-only KV transfer skipped %d pages "
                "but only %d are resident; prefilling locally",
                seq.request_id, rk.skip_pages, seq.cached_len // ps,
            )
            seq.remote_kv = None
            return
        start = max(seq.cached_len // ps, rk.skip_pages)
        end = min(n_pages, rk.skip_pages + len(rk.pages))
        if end > start:
            self._inject_page_batch(
                seq.page_ids[start:end],
                [rk.pages[i - rk.skip_pages][0] for i in range(start, end)],
                [rk.pages[i - rk.skip_pages][1] for i in range(start, end)],
                op="inject",
            )
            self.kv.mark_filled(seq.page_ids[start:end])
        seq.remote_kv = None  # drop the host copy the moment it's injected
        seq.remote_prefilled = True
        self._finish_first_token(seq, rk.first_token)

    def _dispatch_prefill_chunk(
        self, batch: list[Sequence]
    ) -> _PendingPrefill | None:
        """One batched prefill dispatch: up to ``prefill_batch`` PREFILL
        sequences each contribute their next ``prefill_chunk``-token
        slice of prompt. Rows/tokens are bucketed so steady state hits a
        small set of compiled variants; rows whose prompt completes this
        chunk get their first token sampled (per-row sampling params) and
        graduate to decode when the pending result is consumed."""
        cfg = self.cfg
        ps = cfg.page_size
        rows = cfg.rows_bucket_for(len(batch))
        sizes = [
            min(len(s.prompt) - s.prefill_sent, cfg.prefill_chunk)
            for s in batch
        ]
        bucket = cfg.bucket_for(max(sizes))
        tokens = np.zeros((rows, bucket), np.int32)
        positions = np.full((rows, bucket), -1, np.int32)
        table = np.zeros((rows, cfg.max_pages_per_seq), np.int32)
        last_idx = np.zeros(rows, np.int32)
        seeds = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.ones(rows, np.float32)
        completed: list[tuple[int, Sequence]] = []
        for i, seq in enumerate(batch):
            self._apply_uploads(seq)
            n = sizes[i]
            start = seq.prefill_sent
            tokens[i, :n] = seq.prompt[start : start + n]
            positions[i, :n] = np.arange(start, start + n)
            table[i, : len(seq.page_ids)] = seq.page_ids
            last_idx[i] = n - 1
            seq.prefill_sent = start + n
            if seq.prefill_sent == len(seq.prompt):
                completed.append((i, seq))
            so = seq.stop.sampling_options
            seeds[i] = seq.sample_seed & 0x7FFFFFFF
            temp[i] = so.temperature if so.temperature is not None else 0.0
            top_k[i] = so.top_k or 0
            top_p[i] = so.top_p if so.top_p is not None else 1.0

        attn_pages = cfg.page_bucket_for(
            max((s.prefill_sent + ps - 1) // ps for s in batch)
        )
        want_lp = any(
            self._wants_logprobs(seq) is not None for seq in batch
        )
        n_variants = len(self._prefill_fns)
        fn = self._prefill_fn(rows, bucket, attn_pages, want_lp)
        fresh = len(self._prefill_fns) > n_variants
        self._flush_offloads()
        prof = self.profiler
        t0 = prof.begin("prefill") if prof is not None else 0.0
        ys, self.k_cache, self.v_cache = fn(
            self.params,
            self.k_cache,
            self.v_cache,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(table),
            jnp.asarray(seeds),
            jnp.asarray(last_idx),
            jnp.asarray(temp),
            jnp.asarray(top_k),
            jnp.asarray(top_p),
        )
        dispatched_at = (
            prof.end("prefill", t0, fresh) if prof is not None else 0.0
        )
        if self.flight is not None:
            self.flight.record(
                "dispatch",
                dispatch="prefill",
                rows=len(batch),
                tokens=int(sum(sizes)),
                completing=len(completed),
            )
        # Pages this chunk fully covered are now filled *in dispatch
        # order*: sharers gated on them may dispatch reads from the next
        # iteration on (prefix sharing, docs/prefix_sharing.md).
        newly_filled: list[int] = []
        for seq in batch:
            n_full = seq.prefill_sent // ps
            if n_full > seq.fill_marked:
                newly_filled.extend(seq.page_ids[seq.fill_marked : n_full])
                seq.fill_marked = n_full
        if newly_filled:
            self.kv.mark_filled(newly_filled)
        return _PendingPrefill(
            ys=ys,
            completed=completed,
            want_lp=want_lp,
            dispatched_at=dispatched_at,
        )

    def _consume_prefill(self, pending: _PendingPrefill) -> None:
        """Host sync of a prefill chunk: sample-complete rows emit their
        first token and join decode. Runs after the decode window for
        this iteration has been dispatched, so the sync overlaps device
        compute instead of serializing ahead of it."""
        if not pending.completed:
            return
        if pending.want_lp:
            toks, lps, top_ids, top_lps = (np.asarray(y) for y in pending.ys)  # dynlint: sync-point(prefill consume)
        else:
            toks = np.asarray(pending.ys[0])  # dynlint: sync-point(prefill consume)
        if self.profiler is not None:
            self.profiler.consume("prefill", pending.dispatched_at)
        if self.flight is not None:
            self.flight.record(
                "consume", dispatch="prefill", completed=len(pending.completed)
            )
        for i, seq in pending.completed:
            n_top = self._wants_logprobs(seq)
            pack = (
                self._lp_pack(
                    n_top, lps[i : i + 1],
                    top_ids[i : i + 1], top_lps[i : i + 1],
                )
                if pending.want_lp and n_top is not None
                else None
            )
            self._finish_first_token(seq, int(toks[i]), pack)

    # ----------------------------------------------------------------- decode
    @staticmethod
    def _needs_sampler(seq: Sequence) -> bool:
        """True when the row needs the full penalty/top-k/top-p sampler
        (vs the greedy fast path)."""
        so = seq.stop.sampling_options
        return bool(
            (so.temperature or 0.0) > 0.0
            or so.frequency_penalty
            or so.presence_penalty
            or (so.repetition_penalty or 1.0) != 1.0
        )

    def _stop_gates(self, seq: Sequence, g0: int) -> tuple[int, int]:
        """On-device stop gates for a row whose window starts with ``g0``
        tokens already generated. Gates are window-step indices t
        (0-based): EOS is actionable at t >= eos_gate (mirrors
        check_stop's min_tokens rule), and the row's max_tokens budget
        runs out after the token sampled at t == budget_gate."""
        sc = seq.stop.stop_conditions
        eos_gate = max((sc.min_tokens or 0) - g0 - 1, 0)
        max_tokens = sc.max_tokens or self.cfg.default_max_tokens
        budget_gate = max(max_tokens - g0 - 1, 0)
        return eos_gate, budget_gate

    def _stop_set(self, seq: Sequence) -> list[int]:
        """The row's on-device stop-token set (static for its lifetime;
        a chained window reuses the already-built array). Overflowing
        sets truncate — the host's check_stop remains authoritative."""
        sc = seq.stop.stop_conditions
        if sc.ignore_eos:
            return []
        stops = list(self.cfg.eos_token_ids) + list(sc.stop_token_ids)
        return stops[: self.cfg.device_stop_width]

    def _resolve_shared_tail(self, seq: Sequence) -> bool:
        """Copy-on-write before the first divergent write: the row's
        next decode token lands inside a page it attached read-shared
        (radix partial-tail match). Sole holder ⇒ the page just leaves
        the index (content offloads to G2 first); shared ⇒ allocate a
        replacement and duplicate it device-to-device — ONE dispatch,
        stream-ordered ahead of the decode window that diverges it.
        False when the pool can't supply the copy target (hard stall)."""
        pid = seq.shared_tail_pid
        new_pid = self.kv.make_private(pid)
        if new_pid is None:
            return False
        if new_pid != pid:
            idx = seq.page_ids.index(pid)
            self._flush_offloads()
            prof = self.profiler
            if prof is not None:
                fresh = prof.first_variant("cow", 0)
                t0 = prof.begin("kv_move")
            self.k_cache, self.v_cache = self._cow_pages(
                self.k_cache,
                self.v_cache,
                jnp.asarray(pid, jnp.int32),
                jnp.asarray(new_pid, jnp.int32),
            )
            if prof is not None:
                prof.end("kv_move", t0, fresh)
            seq.page_ids[idx] = new_pid
            self.kv.release_sequence([pid])
            self.kv_page_moves += 1
            self.kv_move_dispatches += 1
            get_telemetry().kv_page_moves.labels("cow").inc()
            get_telemetry().kv_cow_copies.inc()
            if self.flight is not None:
                self.flight.record("cow", req=seq.request_id, slot=seq.slot)
        seq.shared_tail_pid = -1
        return True

    def _dispatch_decode(
        self,
    ) -> tuple[list[_PendingDecode], list[_PendingSpec]]:
        """Dispatch this iteration's decode window(s) over the ACTIVE
        slots: rows are compacted (no dead slots) and partitioned into a
        greedy window and a full-sampler window, each compiled at its
        own row bucket — so decode cost tracks occupancy and a lone
        creative request doesn't drag greedy rows through the sampler.
        With speculation on, rows the drafter has proposals for are
        pulled out of each partition into a verify dispatch instead
        (consumed synchronously; they never chain). Returns the pending
        (unsynced) window dispatches plus the pending verify dispatches;
        ([], []) when nothing could step (no ACTIVE rows / pool dry)."""
        cfg = self.cfg
        ps, K = cfg.page_size, cfg.decode_window
        greedy: list[tuple[Sequence, int, int]] = []  # (seq, wpos, cap)
        sampler: list[tuple[Sequence, int, int]] = []
        for seq in self.sched.slots:
            if seq is None or seq.state is not SeqState.ACTIVE:
                continue
            if seq.shared_tail_pid >= 0 and not self._resolve_shared_tail(seq):
                # The shared tail page must be private before this row's
                # first decode write lands in it, and the COW copy found
                # the pool dry: hard-stall the row (same grace clock as
                # a dry page allocation).
                seq.stalled = True
                if not seq.stalled_since:
                    seq.stalled_since = time.time()
                    if self.flight is not None:
                        self.flight.record(
                            "stall_start", req=seq.request_id, slot=seq.slot
                        )
                continue
            wpos = len(seq.tokens) - 1  # position of the token being fed
            # Provision the whole window up front (best effort: partial
            # allocation still lets the row run until its pages end).
            self.sched.ensure_pages_until(seq, wpos + K - 1)
            cap = min(cfg.max_model_len, len(seq.page_ids) * ps) - 1
            if cap < wpos:
                if wpos // ps >= self.kv.num_pages:
                    # The row's own context now exceeds the ENTIRE pool:
                    # no preemption or wait can ever feed its next token
                    # on this engine. The pool is this deployment's hard
                    # context capacity — close the stream with what it
                    # has (mirrors the max_model_len LENGTH) instead of
                    # stalling the slot forever.
                    log.warning(
                        "request %s reached the KV pool's context "
                        "capacity (%d pages) at %d tokens; finishing "
                        "with length",
                        seq.request_id, self.kv.num_pages, wpos,
                    )
                    self.sched.finish(seq, FinishReason.LENGTH)
                    continue
                # Hard stall: the row cannot even feed its next token.
                # Start (or keep) the preemption grace clock.
                seq.stalled = True
                if not seq.stalled_since:
                    seq.stalled_since = time.time()
                    if self.flight is not None:
                        self.flight.record(
                            "stall_start", req=seq.request_id, slot=seq.slot
                        )
                continue  # pool dry: this slot idles one window
            seq.stalled = len(seq.page_ids) * ps < min(
                wpos + K, cfg.max_model_len
            )
            if seq.stalled_since and self.flight is not None:
                self.flight.record(
                    "stall_end", req=seq.request_id, slot=seq.slot
                )
            seq.stalled_since = 0.0  # progressing (even if window-capped)
            part = sampler if self._needs_sampler(seq) else greedy
            part.append((seq, wpos, cap))
        spec_parts: list[tuple[list, bool]] = []
        if self._spec is not None:
            greedy, g_spec = self._extract_spec_rows(greedy)
            sampler, s_spec = self._extract_spec_rows(sampler)
            spec_parts = [(p, fs) for p, fs in ((g_spec, False), (s_spec, True)) if p]
            if len(self._spec) > 4 * cfg.max_decode_slots:
                self._spec.retain(
                    s.request_id for s in self.sched.slots if s is not None
                )
        spec_out = [
            self._dispatch_spec(part, fs) for part, fs in spec_parts
        ]
        out: list[_PendingDecode] = []
        # A window is chainable only when it is the iteration's single
        # decode dispatch — a concurrent verify pass (like a second
        # partition) means the row set will be re-planned next round.
        solo = (bool(greedy) != bool(sampler)) and not spec_out
        for part, full_sampler in ((greedy, False), (sampler, True)):
            if part:
                out.append(self._dispatch_partition(part, full_sampler, solo))
        return out, spec_out

    # ------------------------------------------------------------ speculation
    def _extract_spec_rows(self, part):
        """Split one decode partition into (plain rows, speculative
        rows): a row speculates when the controller wants to probe it
        AND the drafter proposes at least one token that fits the row's
        page/model-length capacity. The drafts' KV positions are
        provisioned here (best effort — a dry pool just shortens the
        draft; the verify pass still always emits >= 1 token)."""
        ps = self.cfg.page_size
        plain, spec = [], []
        for seq, wpos, cap in part:
            drafts = (
                self._spec.propose(seq)
                if self._spec.wants_draft(seq)
                else []
            )
            if drafts:
                self.sched.ensure_pages_until(seq, wpos + len(drafts))
                cap = min(
                    self.cfg.max_model_len, len(seq.page_ids) * ps
                ) - 1
                g = min(len(drafts), cap - wpos, self.cfg.spec_max_draft)
                if g >= 1:
                    spec.append((seq, wpos, cap, drafts[:g]))
                    continue
            plain.append((seq, wpos, cap))
        return plain, spec

    def _dispatch_spec(self, part, full_sampler: bool) -> _PendingSpec:
        """Build + dispatch one batched verify pass: each row feeds its
        last confirmed token plus its draft tokens at consecutive
        absolute positions (one chunked-prefill-shaped dispatch per row
        group). No host sync here; :meth:`_consume_spec` runs in the
        same iteration."""
        cfg = self.cfg
        ps = cfg.page_size
        rows = cfg.decode_rows_bucket_for(len(part))
        kb = cfg.spec_draft_bucket_for(max(len(d) for _, _, _, d in part))
        T = kb + 1
        tokens = np.zeros((rows, T), np.int32)
        positions = np.full((rows, T), -1, np.int32)
        table = np.zeros((rows, cfg.max_pages_per_seq), np.int32)
        n_drafts = np.zeros(rows, np.int32)
        slot_map = np.full(rows, cfg.max_decode_slots, np.int32)
        seeds = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.ones(rows, np.float32)
        freq = np.zeros(rows, np.float32)
        pres = np.zeros(rows, np.float32)
        rep = np.ones(rows, np.float32)
        stepped: list[tuple[Sequence, int, int]] = []
        max_pages = 1
        for r, (seq, wpos, _cap, drafts) in enumerate(part):
            g = len(drafts)
            tokens[r, 0] = seq.last_token()
            tokens[r, 1 : g + 1] = drafts
            positions[r, : g + 1] = np.arange(wpos, wpos + g + 1)
            table[r, : len(seq.page_ids)] = seq.page_ids
            n_drafts[r] = g
            slot_map[r] = seq.slot
            max_pages = max(max_pages, (wpos + g) // ps + 1)
            so = seq.stop.sampling_options
            seeds[r] = seq.sample_seed & 0x7FFFFFFF
            temp[r] = so.temperature if so.temperature is not None else 0.0
            top_k[r] = so.top_k or 0
            top_p[r] = so.top_p if so.top_p is not None else 1.0
            freq[r] = so.frequency_penalty or 0.0
            pres[r] = so.presence_penalty or 0.0
            rep[r] = so.repetition_penalty or 1.0
            stepped.append((seq, g, r))
        want_lp = any(
            self._wants_logprobs(seq) is not None for seq, _, _ in stepped
        )
        n_variants = len(self._spec_fns)
        fn = self._spec_fn(
            rows, kb, cfg.page_bucket_for(max_pages), full_sampler, want_lp
        )
        fresh = len(self._spec_fns) > n_variants
        self._flush_offloads()
        prof = self.profiler
        t0 = prof.begin("spec_verify") if prof is not None else 0.0
        if full_sampler:
            ys, self.k_cache, self.v_cache, self._counts = fn(
                self.params, self.k_cache, self.v_cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(table), jnp.asarray(n_drafts), self._counts,
                jnp.asarray(slot_map), jnp.asarray(seeds),
                jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
                jnp.asarray(freq), jnp.asarray(pres), jnp.asarray(rep),
            )
        else:
            ys, self.k_cache, self.v_cache = fn(
                self.params, self.k_cache, self.v_cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(table), jnp.asarray(n_drafts),
            )
        dispatched_at = (
            prof.end("spec_verify", t0, fresh) if prof is not None else 0.0
        )
        if self.flight is not None:
            self.flight.record(
                "dispatch", dispatch="spec_verify", rows=len(part), draft_bucket=kb
            )
        self.steps += T
        self.spec_dispatches += 1
        get_telemetry().decode_batch_rows.observe(len(part))
        return _PendingSpec(
            ys=ys,
            stepped=stepped,
            full_sampler=full_sampler,
            want_lp=want_lp,
            dispatched_at=dispatched_at,
        )

    def _consume_spec(self, pending: _PendingSpec) -> None:
        """Host sync of one verify pass: the device already computed the
        acceptance (longest prefix where draft == target, plus the first
        correction token — :func:`spec_accept_length` /
        :func:`spec_verify_tokens`, the same rule that gated the
        on-device penalty counts); the host emits those tokens, rewinds
        state past rejected positions, and feeds the outcome back to
        the adaptive controller. The authoritative host ``check_stop``
        still gates every emitted token (EOS / stop ids / budget),
        exactly as in decode."""
        if pending.want_lp:
            targets, n_emits, lps, top_ids, top_lps = (
                np.asarray(y) for y in pending.ys  # dynlint: sync-point(spec verify consume)
            )
        else:
            targets = np.asarray(pending.ys[0])  # dynlint: sync-point(spec verify consume)
            n_emits = np.asarray(pending.ys[1])  # dynlint: sync-point(spec verify consume)
        if self.profiler is not None:
            self.profiler.consume("spec_verify", pending.dispatched_at)
        if self.flight is not None:
            self.flight.record(
                "consume", dispatch="spec_verify", rows=len(pending.stepped)
            )
        tel = get_telemetry()
        for seq, g, row in pending.stepped:
            if seq.state is not SeqState.ACTIVE or seq.pending_finish is not None:
                continue
            tgt = targets[row]
            n_emit = int(n_emits[row])
            accepted = n_emit - 1
            kept: list[int] = []
            reason = None
            for i in range(n_emit):
                token = int(tgt[i])
                kept.append(token)
                seq.tokens.append(token)
                seq.generated += 1
                reason = self.sched.check_stop(seq, token)
                if reason is not None:
                    break
            if n_emit - len(kept):
                # Tokens past a host-detected stop: computed, discarded.
                self.wasted_steps += n_emit - len(kept)
                tel.decode_wasted_steps.inc(n_emit - len(kept))
            seq.spec_dispatches += 1
            seq.spec_draft_tokens += g
            seq.spec_accepted_tokens += accepted
            seq.spec_emitted_tokens += len(kept)
            self.spec_row_dispatches += 1
            self.spec_draft_tokens += g
            self.spec_accepted_tokens += accepted
            self.spec_emitted_tokens += len(kept)
            tel.spec_draft_tokens.inc(g)
            tel.spec_accepted_tokens.inc(accepted)
            tel.spec_tokens_per_dispatch.observe(len(kept))
            if self.flight is not None:
                self.flight.record(
                    "spec_accept",
                    req=seq.request_id,
                    proposed=g,
                    accepted=accepted,
                    emitted=len(kept),
                )
            self._spec.record(seq, proposed=g, accepted=accepted)
            self.sched.register_full_pages(seq)
            n_top = self._wants_logprobs(seq)
            pack = None
            if n_top is not None and kept:
                n = len(kept)
                pack = self._lp_pack(
                    n_top, lps[row, :n], top_ids[row, :n], top_lps[row, :n]
                )
            if kept:
                now = time.time()
                if seq.last_emit_at:
                    tbt = max(now - seq.last_emit_at, 0.0) / len(kept)
                    tel.time_between_tokens.observe(tbt)
                seq.last_emit_at = now
            seq.emit(kept, None, pack)
            if reason is not None:
                # No chained window can be in flight over a spec row
                # (spec rows break the chain), so finishing — and the
                # page release it implies — is safe right here.
                self.sched.finish(seq, reason)
            else:
                self._rewind_spec_pages(seq)

    def _rewind_spec_pages(self, seq: Sequence) -> None:
        """Page-granular rewind after a rejection: pages provisioned for
        draft positions beyond the accepted prefix go back to the pool
        when the rejection crossed a page boundary. Only unregistered
        tail pages can be trailing here (registration stops at the last
        *full* page below the confirmed write head), so the release
        can't disturb the reuse index; the KV slots inside the kept tail
        page are overwritten in place as decode advances."""
        ps = self.cfg.page_size
        keep = (len(seq.tokens) - 1) // ps + 1
        if len(seq.page_ids) > keep:
            extra = seq.page_ids[keep:]
            del seq.page_ids[keep:]
            self.kv.release_sequence(extra)
            if self.flight is not None:
                self.flight.record(
                    "spec_rewind", req=seq.request_id, pages=len(extra)
                )

    def _dispatch_partition(
        self,
        part: list[tuple[Sequence, int, int]],
        full_sampler: bool,
        solo: bool,
    ) -> _PendingDecode:
        """Build + dispatch one compacted decode window (no host sync)."""
        cfg = self.cfg
        ps, K, S = cfg.page_size, cfg.decode_window, cfg.device_stop_width
        rows = cfg.decode_rows_bucket_for(len(part))
        tokens = np.zeros(rows, np.int32)
        positions = np.full(rows, -1, np.int32)
        max_pos = np.full(rows, -1, np.int32)
        table = np.zeros((rows, cfg.max_pages_per_seq), np.int32)
        # Pad rows map to the scratch counts row (B) so their scatter
        # can't touch a live slot.
        slot_map = np.full(rows, cfg.max_decode_slots, np.int32)
        stop_set = np.full((rows, S), -1, np.int32)
        eos_gate = np.zeros(rows, np.int32)
        budget_gate = np.full(rows, K, np.int32)  # pad: never fires
        seeds = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.ones(rows, np.float32)
        freq = np.zeros(rows, np.float32)
        pres = np.zeros(rows, np.float32)
        rep = np.ones(rows, np.float32)

        stepped: list[tuple[Sequence, int, int]] = []
        max_pages = 1
        capacity_capped = False
        for r, (seq, wpos, cap) in enumerate(part):
            capacity_capped = capacity_capped or cap < wpos + K
            tokens[r] = seq.last_token()
            positions[r] = wpos
            max_pos[r] = cap
            table[r, : len(seq.page_ids)] = seq.page_ids
            slot_map[r] = seq.slot
            max_pages = max(max_pages, (min(wpos + K, cap + 1) + ps - 1) // ps)
            stops = self._stop_set(seq)
            stop_set[r, : len(stops)] = stops
            eos_gate[r], budget_gate[r] = self._stop_gates(seq, seq.generated)
            so = seq.stop.sampling_options
            seeds[r] = seq.sample_seed & 0x7FFFFFFF
            temp[r] = so.temperature if so.temperature is not None else 0.0
            top_k[r] = so.top_k or 0
            top_p[r] = so.top_p if so.top_p is not None else 1.0
            freq[r] = so.frequency_penalty or 0.0
            pres[r] = so.presence_penalty or 0.0
            rep[r] = so.repetition_penalty or 1.0
            stepped.append((seq, min(K, cap - wpos + 1), r))

        want_lp = any(
            self._wants_logprobs(seq) is not None for seq, _, _ in stepped
        )
        n_variants = len(self._decode_fns)
        fn = self._decode_fn(
            rows, cfg.page_bucket_for(max_pages), full_sampler, want_lp
        )
        fresh = len(self._decode_fns) > n_variants
        self._flush_offloads()
        prof = self.profiler
        t0 = prof.begin("decode") if prof is not None else 0.0
        sampler_args = (seeds, temp, top_k, top_p, freq, pres, rep)
        if full_sampler:
            (ys, self.k_cache, self.v_cache, self._counts,
             tok_dev, pos_dev) = fn(
                self.params, self.k_cache, self.v_cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(max_pos), jnp.asarray(table),
                jnp.asarray(seeds), self._counts, jnp.asarray(slot_map),
                jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
                jnp.asarray(freq), jnp.asarray(pres), jnp.asarray(rep),
                jnp.asarray(stop_set), jnp.asarray(eos_gate),
                jnp.asarray(budget_gate),
            )
        else:
            ys, self.k_cache, self.v_cache, tok_dev, pos_dev = fn(
                self.params, self.k_cache, self.v_cache,
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(max_pos), jnp.asarray(table),
                jnp.asarray(stop_set), jnp.asarray(eos_gate),
                jnp.asarray(budget_gate),
            )
        dispatched_at = (
            prof.end("decode", t0, fresh) if prof is not None else 0.0
        )
        if self.flight is not None:
            self.flight.record(
                "dispatch", dispatch="decode", rows=len(part), bucket=rows
            )
        self.steps += K
        get_telemetry().decode_batch_rows.observe(len(part))
        return _PendingDecode(
            ys=ys,
            tokens_dev=tok_dev,
            positions_dev=pos_dev,
            stepped=stepped,
            rows=rows,
            full_sampler=full_sampler,
            want_lp=want_lp,
            solo=solo,
            capacity_capped=capacity_capped,
            stop_tokens=stop_set,
            sampler_args=sampler_args if full_sampler else None,
            slot_map=slot_map if full_sampler else None,
            dispatched_at=dispatched_at,
        )

    def _can_chain(self) -> bool:
        """Whether the next window may launch straight from the inflight
        window's device carry, before the host syncs. Requires a stable
        steady state: nothing waiting or prefilling, no cancellations,
        a single (solo) partition, and at least one row the host knows
        will outlive the inflight window (otherwise the chained window
        would compute only discards)."""
        p = self._inflight
        if p is None or not p.solo or not self.cfg.chained_decode:
            return False
        if p.capacity_capped:
            return False  # a capped row's carry is dead but resumable
        if not self._submit_q.empty() or self.sched.waiting:
            return False
        if self._spec is not None:
            # Speculative rows break the chain exactly like capacity-
            # capped rows: a chained window would step token-by-token
            # past positions a verify pass could cover in one dispatch,
            # and the drafter must re-plan from the freshly consumed
            # tokens each round. Rows whose drafting is backed off
            # (lookup keeps missing) chain normally.
            for s, _, _ in p.stepped:
                if s.state is SeqState.ACTIVE and self._spec.wants_draft(s):
                    return False
        stepped_seqs = {id(seq) for seq, _, _ in p.stepped}
        now = time.time()
        for s in self.sched.slots:
            if s is None:
                continue
            if s.state is SeqState.PREFILL:
                return False
            if s.is_cancelled():
                return False
            if s.deadline_unix and now >= s.deadline_unix:
                return False  # break the chain so the deadline is enforced
            if s.state is SeqState.ACTIVE and id(s) not in stepped_seqs:
                # A row joined (finished prefill) or sat out (stalled)
                # after the chain started; chaining over the old row set
                # would starve it — rebuild a fresh compacted window.
                return False
        K = self.cfg.decode_window
        for seq, n_valid, _ in p.stepped:
            sc = seq.stop.stop_conditions
            max_tokens = sc.max_tokens or self.cfg.default_max_tokens
            if n_valid >= K and max_tokens - seq.generated > K:
                return True  # a survivor makes the chained window useful
        return False

    def _dispatch_chained(
        self, pending: _PendingDecode
    ) -> _PendingDecode | None:
        """Dispatch window N+1 over window N's rows using N's on-device
        carry (tokens/positions) as inputs — no host round-trip. The
        host view of these rows lags one window: positions advance by
        exactly ``decode_window`` for every surviving row (a row the
        device stopped carries position -1 and computes into discards
        the host skips at consume). Pages are provisioned one extra
        window ahead; returns None (chain break) when the pool can't
        cover a row."""
        cfg = self.cfg
        ps, K = cfg.page_size, cfg.decode_window
        rows = pending.rows
        max_pos = np.full(rows, -1, np.int32)
        table = np.zeros((rows, cfg.max_pages_per_seq), np.int32)
        stop_set = pending.stop_tokens  # same rows, same stop sets
        eos_gate = np.zeros(rows, np.int32)
        budget_gate = np.full(rows, K, np.int32)
        stepped: list[tuple[Sequence, int, int]] = []
        max_pages = 1
        capacity_capped = False
        for seq, _, r in pending.stepped:
            wpos = len(seq.tokens) - 1 + K  # host view + inflight window
            self.sched.ensure_pages_until(seq, wpos + K - 1)
            cap = min(cfg.max_model_len, len(seq.page_ids) * ps) - 1
            if cap < wpos:
                return None  # pool dry: consume + rebuild instead
            capacity_capped = capacity_capped or cap < wpos + K
            max_pos[r] = cap
            table[r, : len(seq.page_ids)] = seq.page_ids
            max_pages = max(max_pages, (min(wpos + K, cap + 1) + ps - 1) // ps)
            eos_gate[r], budget_gate[r] = self._stop_gates(
                seq, seq.generated + K
            )
            stepped.append((seq, min(K, cap - wpos + 1), r))
        n_variants = len(self._decode_fns)
        fn = self._decode_fn(  # dynlint: recompile-hazard(chained window reuses the dispatched bucket)
            rows,
            cfg.page_bucket_for(max_pages),
            pending.full_sampler,
            pending.want_lp,
        )
        fresh = len(self._decode_fns) > n_variants
        self._flush_offloads()
        prof = self.profiler
        t0 = prof.begin("decode") if prof is not None else 0.0
        if pending.full_sampler:
            seeds, temp, top_k, top_p, freq, pres, rep = pending.sampler_args
            (ys, self.k_cache, self.v_cache, self._counts,
             tok_dev, pos_dev) = fn(
                self.params, self.k_cache, self.v_cache,
                pending.tokens_dev, pending.positions_dev,
                jnp.asarray(max_pos), jnp.asarray(table),
                jnp.asarray(seeds), self._counts, jnp.asarray(pending.slot_map),
                jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
                jnp.asarray(freq), jnp.asarray(pres), jnp.asarray(rep),
                jnp.asarray(stop_set), jnp.asarray(eos_gate),
                jnp.asarray(budget_gate),
            )
        else:
            ys, self.k_cache, self.v_cache, tok_dev, pos_dev = fn(
                self.params, self.k_cache, self.v_cache,
                pending.tokens_dev, pending.positions_dev,
                jnp.asarray(max_pos), jnp.asarray(table),
                jnp.asarray(stop_set), jnp.asarray(eos_gate),
                jnp.asarray(budget_gate),
            )
        dispatched_at = (
            prof.end("decode", t0, fresh) if prof is not None else 0.0
        )
        if self.flight is not None:
            self.flight.record(
                "dispatch",
                dispatch="decode",
                rows=len(stepped),
                bucket=rows,
                chained=True,
            )
        self.steps += K
        get_telemetry().decode_batch_rows.observe(len(stepped))
        return _PendingDecode(
            ys=ys,
            tokens_dev=tok_dev,
            positions_dev=pos_dev,
            stepped=stepped,
            rows=rows,
            full_sampler=pending.full_sampler,
            want_lp=pending.want_lp,
            solo=True,
            capacity_capped=capacity_capped,
            stop_tokens=stop_set,
            sampler_args=pending.sampler_args,
            slot_map=pending.slot_map,
            dispatched_at=dispatched_at,
        )

    def _consume_decode(self, pending: _PendingDecode) -> None:
        """Host sync of one decode window: emit kept tokens, run the
        authoritative check_stop, register completed pages. A stop found
        while a chained successor is still in flight defers the finish
        (page release) until that successor is force-consumed — the
        device already parked the row at position -1, so the successor
        writes nothing for it."""
        K = self.cfg.decode_window
        if pending.want_lp:
            sampled, lps, top_ids, top_lps = (
                np.asarray(y) for y in pending.ys  # dynlint: sync-point(decode window consume)
            )
        else:
            sampled = np.asarray(pending.ys[0])  # dynlint: sync-point(decode window consume)
        if self.profiler is not None:
            # The np.asarray above was this window's one host sync.
            self.profiler.consume("decode", pending.dispatched_at)
        tel = get_telemetry()
        finishes: list[Sequence] = []
        wasted = 0
        emitted = 0
        for seq, n_valid, row in pending.stepped:
            if seq.state is not SeqState.ACTIVE or seq.pending_finish is not None:
                wasted += n_valid  # whole window past this row's stop
                continue
            kept: list[int] = []
            reason = None
            for token in sampled[:n_valid, row]:
                token = int(token)
                kept.append(token)
                seq.tokens.append(token)
                seq.generated += 1
                reason = self.sched.check_stop(seq, token)
                if reason is not None:
                    break
            wasted += n_valid - len(kept)
            emitted += len(kept)
            self.sched.register_full_pages(seq)
            n_top = self._wants_logprobs(seq)
            pack = None
            if n_top is not None and kept:
                n = len(kept)
                pack = self._lp_pack(
                    n_top,
                    lps[:n, row],
                    top_ids[:n, row],
                    top_lps[:n, row],
                )
            if kept:
                now = time.time()
                if seq.last_emit_at:
                    tbt = max(now - seq.last_emit_at, 0.0) / len(kept)
                    tel.time_between_tokens.observe(tbt)
                seq.last_emit_at = now
            seq.emit(kept, None, pack)
            if reason is not None:
                seq.pending_finish = reason
                finishes.append(seq)
        if self.flight is not None:
            self.flight.record(
                "consume", dispatch="decode", tokens=emitted, wasted=wasted
            )
        if wasted:
            self.wasted_steps += wasted
            tel.decode_wasted_steps.inc(wasted)
        if finishes:
            # Pages about to be released must not have a window in
            # flight over them: sync the chained successor first (its
            # surviving rows' tokens are consumed normally; rows with a
            # pending finish are skipped above).
            succ, self._inflight = self._inflight, None
            if succ is not None:
                self._consume_decode(succ)
            for seq in finishes:
                reason, seq.pending_finish = seq.pending_finish, None
                self.sched.finish(seq, reason)

    # --------------------------------------------------------------- metrics
    def metrics(self) -> dict:
        m = self.sched.metrics()
        # Occupancy-proportional decode counters (docs/engine_perf.md):
        # bench.py's occupancy sweep and the proportionality tests read
        # these; /metrics exposes the prometheus mirrors.
        m["decode_steps"] = self.steps
        m["decode_wasted_steps"] = self.wasted_steps
        m["kv_page_moves"] = self.kv_page_moves
        m["kv_move_dispatches"] = self.kv_move_dispatches
        m["preemptions"] = self.preempted
        m["kv_leases_active"] = self.kv.active_leases
        m["kv_lease_reclaimed_pages"] = self.kv.lease_reclaimed_pages
        # Fleet-wide prefix sharing (docs/prefix_sharing.md): COW
        # copies, the resident-page high-water mark, and the page-
        # granular admission hit breakdown (shared G1 attach / G2
        # restore / fresh miss); the kv_shared_pages gauge rides in via
        # kv.gauges() with the other KV-tier gauges.
        m["kv_cow_copies"] = self.kv.cow_copies
        m["kv_peak_pages"] = self.kv.peak_active_pages
        m["kv_prefix_hits_shared"] = self.kv.prefix_hits["shared"]
        m["kv_prefix_hits_restore"] = self.kv.prefix_hits["restore"]
        m["kv_prefix_hits_miss"] = self.kv.prefix_hits["miss"]
        m["compiled_decode_variants"] = len(self._decode_fns)
        m["compiled_prefill_variants"] = len(self._prefill_fns)
        # Per-dispatch profiler mirror (docs/observability.md): per-kind
        # host-gap / in-flight percentiles over the recent window plus
        # compile attribution — the same numbers the dynamo_dispatch_*
        # prometheus series aggregate, in pullable form for bench.py's
        # per-line dispatch field and sim/fit.py's bench fitting.
        # decode_window rides along so a per-dispatch time converts to a
        # per-token ITL without a span file.
        if self.profiler is not None:
            m["dispatch"] = self.profiler.summary()
        m["decode_window"] = self.cfg.decode_window
        # Speculative decoding (docs/speculative.md): acceptance rate =
        # accepted/draft, tokens-per-dispatch = emitted/dispatches.
        m["spec_dispatches"] = self.spec_dispatches
        # Per-ROW verify participations: tokens-per-dispatch on the
        # per-row basis the sim's service model consumes is
        # emitted / row_dispatches (a batched dispatch over N rows is N
        # row-dispatches — dividing by the device-dispatch count would
        # conflate batch occupancy with speculation speedup).
        m["spec_row_dispatches"] = self.spec_row_dispatches
        m["spec_draft_tokens"] = self.spec_draft_tokens
        m["spec_accepted_tokens"] = self.spec_accepted_tokens
        m["spec_emitted_tokens"] = self.spec_emitted_tokens
        m["compiled_spec_variants"] = len(self._spec_fns)
        if self.host_pool is not None:
            m["host_cache_resident"] = self.host_pool.resident
            m["host_cache_hits"] = self.host_pool.hits
            m["host_cache_stores"] = self.host_pool.stores
        return m
