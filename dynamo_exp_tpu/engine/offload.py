"""G2 host-RAM KV tier + async device↔host copy stream.

Capability parity with the reference's two-tier KV storage manager
(``/root/reference/lib/llm/src/kv/manager.rs:22-168`` — G1 device / G2
host — and the ``CopyStream`` batched async block copies in
``kv/layer.rs:619-2066`` backed by ``kernels/block_copy.cu``), redesigned
for TPU:

- The host tier is one preallocated numpy pool per K/V (the reference
  uses pinned host memory via ``cuda_malloc_host``; on TPU-VM plain
  numpy is already in host RAM and ``jax.device_put`` DMAs from it).
- Device→host movement = a jitted per-page gather (XLA dynamic-slice on
  the page axis) dispatched on the engine loop thread, then materialized
  (``np.asarray``) on a background copy thread so eviction never blocks
  the decode loop. Dispatch-order semantics guarantee the gather reads
  the page before any later donated forward overwrites it.
- Host→device movement = a jitted scatter (``.at[:, pid].set``) of the
  host page into a freshly allocated device page, dispatched before the
  prefill that consumes it.

Pages are keyed by the same chained sequence hash used for G1 prefix
reuse and router events (``tokens.py``), so the three tiers (device,
host, remote-worker-via-router) share one content-addressing scheme.
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import OrderedDict

import numpy as np

log = logging.getLogger(__name__)


class HostKvPool:
    """Fixed-capacity host-RAM page pool, content-addressed, LRU-evicted.

    Thread-safe: written by the copy thread, read (matched/fetched) by
    the engine loop thread.
    """

    def __init__(self, num_pages: int, page_shape: tuple[int, ...], dtype):
        self.num_pages = num_pages
        self._k = np.zeros((num_pages,) + page_shape, dtype)
        self._v = np.zeros((num_pages,) + page_shape, dtype)
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        # seq_hash -> host slot; OrderedDict doubles as the LRU (oldest first).
        self._by_hash: OrderedDict[int, int] = OrderedDict()
        self._lock = threading.Lock()
        # Metrics.
        self.stores = 0
        self.hits = 0
        self.evictions = 0

    def __contains__(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._by_hash

    @property
    def resident(self) -> int:
        with self._lock:
            return len(self._by_hash)

    def store(self, seq_hash: int, k_page: np.ndarray, v_page: np.ndarray) -> None:
        """Insert one page; evicts the LRU page when full. Idempotent per
        hash (a page already resident is refreshed, not duplicated)."""
        with self._lock:
            slot = self._by_hash.get(seq_hash)
            if slot is None:
                if self._free:
                    slot = self._free.pop()
                else:
                    _, slot = self._by_hash.popitem(last=False)
                    self.evictions += 1
                self._by_hash[seq_hash] = slot
            self._by_hash.move_to_end(seq_hash)
            self._k[slot] = k_page
            self._v[slot] = v_page
            self.stores += 1

    def fetch(self, seq_hash: int) -> tuple[np.ndarray, np.ndarray] | None:
        """Copy one page out (the copy pins the content against a
        concurrent LRU eviction overwriting the slot)."""
        with self._lock:
            slot = self._by_hash.get(seq_hash)
            if slot is None:
                return None
            self._by_hash.move_to_end(seq_hash)
            self.hits += 1
            return self._k[slot].copy(), self._v[slot].copy()

    def match_chain(self, seq_hashes: list[int]) -> list[int]:
        """Longest resident prefix of the hash chain (for extending a G1
        match into G2 without fetching yet)."""
        out: list[int] = []
        with self._lock:
            for h in seq_hashes:
                if h not in self._by_hash:
                    break
                out.append(h)
        return out


class CopyStream:
    """Background device→host materializer.

    The engine loop dispatches the on-device page gather (cheap, async)
    and hands the resulting device arrays here; this thread blocks on the
    transfer (``np.asarray``) and commits the page into the host pool —
    the TPU analogue of the reference's CUDA ``CopyStream`` with
    completion events (``kv/layer.rs:619+``).
    """

    def __init__(self, pool: HostKvPool, max_inflight: int = 256):
        self.pool = pool
        # Bounded: each entry pins a gathered K/V device-array pair, so a
        # burst of evictions outpacing the blocking host transfers must
        # shed load (the tier is a cache — dropping an offload only costs
        # a future recompute) instead of growing HBM pressure unboundedly.
        self._q: queue.Queue = queue.Queue(maxsize=max_inflight)
        self._thread = threading.Thread(
            target=self._run, name="kv-copy-stream", daemon=True
        )
        self._running = True
        self.dropped = 0
        self._thread.start()

    def offload_batch(
        self, seq_hashes: list, k_dev, v_dev, on_synced=None
    ) -> None:
        """Coalesced offload: one gathered [L, n, ps, HkvD] K/V pair
        covering ``len(seq_hashes)`` pages (page axis 1). The worker
        materializes the whole batch with ONE host transfer and commits
        page-by-page — an eviction burst costs one dispatch + one sync
        instead of one per page. ``on_synced`` (if given) fires right
        after that existing host transfer completes — the dispatch
        profiler's consume point for the ``offload`` kind, so in-flight
        timing rides the sync the stream was doing anyway."""
        try:
            self._q.put_nowait((list(seq_hashes), k_dev, v_dev, on_synced))
        except queue.Full:
            self.dropped += len(seq_hashes)

    def drain(self, timeout: float = 10.0) -> None:
        """Block until every queued offload has *committed* (tests)."""
        import time

        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.005)

    def stop(self) -> None:
        """Stop the stream. Offloads still queued are discarded — the
        tier is a cache, so shutdown loses nothing but future hits."""
        self._running = False
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass  # worker is mid-backlog; it re-checks _running per item
        self._thread.join(timeout=10)

    def _run(self) -> None:
        while self._running:
            item = self._q.get()
            try:
                if item is None:
                    return
                seq_hashes, k_dev, v_dev, on_synced = item
                k_np, v_np = np.asarray(k_dev), np.asarray(v_dev)  # dynlint: sync-point(offload copy-thread transfer)
                if on_synced is not None:
                    try:
                        on_synced()
                    except Exception:  # profiling must not break offload
                        log.exception("offload on_synced callback failed")
                for j, h in enumerate(seq_hashes):
                    self.pool.store(h, k_np[:, j], v_np[:, j])
            except Exception:  # never kill the stream on one bad page
                log.exception("KV offload of page(s) %s failed", item[0])
            finally:
                self._q.task_done()
