"""Backend: the detokenizing post-processor wrapping an execution engine.

Capability parity with ``/root/reference/lib/llm/src/backend.rs``: takes
the token-in/token-out engine ("ExecutionContext"), applies incremental
detokenization per streamed token, checks stop conditions — including the
"jail" that withholds text which might be the start of a hidden stop
sequence — and maps finish reasons.
"""

from __future__ import annotations

from typing import Any, AsyncIterator

from .model_card import ModelDeploymentCard
from .protocols.common import BackendInput, FinishReason, LLMEngineOutput
from .runtime.engine import AsyncEngine, AsyncEngineContext, ResponseStream
from .tokenizer import Tokenizer


class StopSequenceJail:
    """Withholds streamed text that may be a prefix of a stop string.

    Hidden stop sequences must never reach the client — including their
    partial beginnings. Text is "jailed" while it could still grow into a
    stop string, released when it diverges, and discarded when a stop
    string completes.
    """

    def __init__(self, stop_sequences: list[str]):
        self._stops = [s for s in stop_sequences if s]
        self._jail = ""

    def feed(self, text: str) -> tuple[str, bool]:
        """Returns (text safe to emit, stop matched)."""
        if not self._stops:
            return text, False
        buf = self._jail + text
        for stop in self._stops:
            idx = buf.find(stop)
            if idx != -1:
                self._jail = ""
                return buf[:idx], True
        # Longest suffix of buf that is a proper prefix of any stop string
        # must stay jailed.
        keep = 0
        for stop in self._stops:
            for k in range(min(len(stop) - 1, len(buf)), 0, -1):
                if buf.endswith(stop[:k]):
                    keep = max(keep, k)
                    break
        if keep:
            self._jail = buf[-keep:]
            return buf[:-keep], False
        self._jail = ""
        return buf, False

    def flush(self) -> str:
        """Release anything still jailed (stream ended without a match)."""
        out, self._jail = self._jail, ""
        return out


class Backend:
    """Engine wrapper: BackendInput -> detokenized LLMEngineOutput stream."""

    def __init__(self, engine: AsyncEngine, tokenizer: Tokenizer):
        self.engine = engine
        self.tokenizer = tokenizer

    @classmethod
    def from_mdc(cls, mdc: ModelDeploymentCard, engine: AsyncEngine) -> "Backend":
        return cls(engine, Tokenizer.from_pretrained(mdc.tokenizer_path or mdc.model_path))

    async def generate(
        self, request: dict | BackendInput, context: AsyncEngineContext | None = None
    ) -> ResponseStream[dict]:
        ctx = context or AsyncEngineContext()
        binput = (
            request
            if isinstance(request, BackendInput)
            else BackendInput.model_validate(request)
        )
        stop = binput.stop_conditions
        stop_ids = set(stop.stop_token_ids)
        engine_stream = await self.engine.generate(binput.to_dict(), ctx)
        decoder = self.tokenizer.decode_stream()
        jail = StopSequenceJail(stop.stop)
        prompt_tokens = len(binput.token_ids)

        async def _gen() -> AsyncIterator[dict]:
            emitted = 0
            finished: FinishReason | None = None
            async for item in engine_stream:
                out = (
                    LLMEngineOutput.from_dict(item) if isinstance(item, dict) else item
                )
                if out.finish_reason is not None:
                    finished = FinishReason(out.finish_reason)
                text_parts: list[str] = []
                for tid in out.token_ids:
                    emitted += 1
                    hit_eos = (
                        tid in stop_ids
                        and not stop.ignore_eos
                        and (stop.min_tokens is None or emitted >= stop.min_tokens)
                    )
                    if not hit_eos:
                        piece = decoder.step(tid)
                        if piece is not None:
                            safe, matched = jail.feed(piece)
                            if safe:
                                text_parts.append(safe)
                            if matched:
                                finished = FinishReason.STOP
                                break
                    else:
                        finished = FinishReason.EOS
                        break
                    if stop.max_tokens is not None and emitted >= stop.max_tokens:
                        finished = finished or FinishReason.LENGTH
                        break
                if finished is not None and finished is not FinishReason.STOP:
                    # Generation ended without a stop-string match: release
                    # any text the jail was still holding as a possible
                    # stop-sequence prefix.
                    text_parts.append(jail.flush())
                if text_parts or out.token_ids or finished:
                    yield LLMEngineOutput(
                        token_ids=out.token_ids,
                        text="".join(text_parts) or None,
                        logprobs=out.logprobs,
                        top_logprobs=out.top_logprobs,
                        finish_reason=finished,
                        prompt_tokens=prompt_tokens if finished else None,
                        completion_tokens=emitted if finished else None,
                    ).to_dict()
                if finished is not None:
                    ctx.stop_generating()
                    break
                if ctx.is_stopped:
                    yield LLMEngineOutput(
                        finish_reason=FinishReason.CANCELLED
                    ).to_dict()
                    break
            else:
                # Engine stream ended without reporting a finish reason:
                # release jailed text and close the stream cleanly.
                tail = jail.flush()
                yield LLMEngineOutput(
                    text=tail or None,
                    finish_reason=FinishReason.EOS,
                    prompt_tokens=prompt_tokens,
                    completion_tokens=emitted,
                ).to_dict()

        return ResponseStream(_gen(), ctx)
