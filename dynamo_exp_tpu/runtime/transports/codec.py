"""Two-part wire codec: JSON header + raw payload, length-prefixed.

Capability parity with the reference's framing
(``/root/reference/lib/llm/src/codec.rs`` /
``lib/runtime/src/pipeline/network/codec/two_part.rs:23-204``): every
message on the wire is a small control header plus an opaque payload, so
the data plane never parses payloads and control messages (stop/kill,
prologue errors) ride the same stream as data frames.

Frame layout (all integers big-endian):

    u8  type        (MsgType)
    u32 header_len
    u32 payload_len
    header bytes    (JSON)
    payload bytes   (opaque)
"""

from __future__ import annotations

import asyncio
import enum
import json
import struct
from dataclasses import dataclass, field

_PREFIX = struct.Struct(">BII")

# Refuse absurd frames rather than allocating unbounded buffers on a
# corrupt or hostile stream.
MAX_HEADER = 1 << 20  # 1 MiB of JSON header
MAX_PAYLOAD = 1 << 30  # 1 GiB payload (KV page transfers are chunked below this)


class MsgType(enum.IntEnum):
    REQUEST = 1  # open a request stream (header: routing info, payload: request)
    FRAME = 2  # one response frame
    COMPLETE = 3  # response stream finished cleanly
    ERROR = 4  # stream aborted; header carries the message
    CONTROL = 5  # upstream control: {"op": "stop"|"kill"} (reference ControlMessage)
    STATS = 6  # stats scrape request/response
    DATA = 7  # generic RPC for the coordinator protocol


class CodecError(RuntimeError):
    pass


@dataclass
class TwoPartMessage:
    msg_type: MsgType
    header: dict = field(default_factory=dict)
    payload: bytes = b""


def encode(msg: TwoPartMessage) -> bytes:
    header = json.dumps(msg.header, separators=(",", ":")).encode()
    if len(header) > MAX_HEADER or len(msg.payload) > MAX_PAYLOAD:
        raise CodecError("frame exceeds size limits")
    return (
        _PREFIX.pack(int(msg.msg_type), len(header), len(msg.payload))
        + header
        + msg.payload
    )


async def read_message(reader: asyncio.StreamReader) -> TwoPartMessage:
    """Read one frame; raises ``asyncio.IncompleteReadError`` at clean EOF."""
    prefix = await reader.readexactly(_PREFIX.size)
    mtype, hlen, plen = _PREFIX.unpack(prefix)
    if hlen > MAX_HEADER or plen > MAX_PAYLOAD:
        raise CodecError(f"oversized frame: header={hlen} payload={plen}")
    header = json.loads(await reader.readexactly(hlen)) if hlen else {}
    payload = await reader.readexactly(plen) if plen else b""
    try:
        return TwoPartMessage(MsgType(mtype), header, payload)
    except ValueError as e:
        raise CodecError(f"unknown message type {mtype}") from e


async def write_message(writer: asyncio.StreamWriter, msg: TwoPartMessage) -> None:
    writer.write(encode(msg))
    await writer.drain()
