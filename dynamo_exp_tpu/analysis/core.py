"""dynlint plumbing: findings, zones, inline waivers, AST helpers.

The waiver grammar is the one reviewable escape hatch every rule
shares (docs/static_analysis.md "Waivers"):

    # dynlint: sync-point(ragged consume)
    # dynlint: determinism(host-only wall-clock report field)

One comment may carry several waivers (space-separated). A waiver
applies to findings of its rule anywhere on the smallest enclosing
*statement* (compound statements count only their header lines), so
multi-line call sites annotate naturally without a body comment ever
covering the header's findings. A bare token without a reason — or an
unknown token — is itself a finding (rule ``waiver-syntax``): the
allowlist only works if every entry says *why*.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass


@dataclass
class Finding:
    """One structured lint finding (rule, location, reason)."""

    rule: str
    file: str
    line: int
    col: int
    message: str
    end_line: int = 0
    waived: bool = False
    reason: str = ""

    def __post_init__(self) -> None:
        if not self.end_line:
            self.end_line = self.line

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "waived": self.waived,
            "reason": self.reason,
        }

    def fingerprint(self, source_lines: list[str]) -> str:
        """Line-number-free identity for ``--baseline`` (survives edits
        elsewhere in the file): rule + file + the flagged line's text."""
        text = ""
        if 1 <= self.line <= len(source_lines):
            text = source_lines[self.line - 1].strip()
        return f"{self.rule}::{self.file}::{text}"


@dataclass(frozen=True)
class Zone:
    """One declared checker zone: a repo-relative file (or directory
    prefix ending in ``/``), optionally narrowed to — or carved around —
    named top-level scopes (functions *or* classes, matched against
    every enclosing scope of the flagged node)."""

    path: str
    include: tuple[str, ...] = ()
    exclude: tuple[str, ...] = ()

    def covers_file(self, rel_path: str) -> bool:
        if self.path.endswith("/"):
            return rel_path.startswith(self.path)
        return rel_path == self.path


def zone_for(zones: tuple[Zone, ...], rel_path: str) -> Zone | None:
    for z in zones:
        if z.covers_file(rel_path):
            return z
    return None


class ScopeIndex:
    """Maps a node to its enclosing defs/classes, so zones can
    include/exclude by scope name without re-walking the tree.

    Zone entries match either a scope's full dotted path
    (``TPUEngine.generate``) or — for top-level scopes only — its bare
    name. A nested helper that happens to reuse an excluded method's
    name (``TPUEngine._loop.<a local 'generate'>``) matches neither, so
    a name collision can never silently exempt hot-path code."""

    def __init__(self, tree: ast.Module):
        # (dotted path, bare name, is_top_level, lo, hi)
        self._spans: list[tuple[str, str, bool, int, int]] = []

        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    dotted = (
                        f"{prefix}.{child.name}" if prefix else child.name
                    )
                    self._spans.append(
                        (
                            dotted,
                            child.name,
                            not prefix,
                            child.lineno,
                            child.end_lineno or child.lineno,
                        )
                    )
                    walk(child, dotted)
                else:
                    walk(child, prefix)

        walk(tree, "")

    def enclosing(self, node: ast.AST) -> set[str]:
        """The match keys of every scope containing the node: dotted
        paths always, bare names for top-level scopes."""
        line = getattr(node, "lineno", 0)
        keys: set[str] = set()
        for dotted, bare, top, lo, hi in self._spans:
            if lo <= line <= hi:
                keys.add(dotted)
                if top:
                    keys.add(bare)
        return keys

    def in_scope(self, node: ast.AST, zone: Zone) -> bool:
        names = self.enclosing(node)
        if zone.include and not names & set(zone.include):
            return False
        if zone.exclude and names & set(zone.exclude):
            return False
        return True


def attr_chain(node: ast.AST) -> tuple[str, ...]:
    """``self.flight.record`` → ("self", "flight", "record"); () when
    the expression is not a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


def dataflow_units(tree: ast.Module) -> list[ast.AST]:
    """The module plus every function and lambda — the per-scope units
    checkers run local dataflow over (pair with :func:`own_nodes`)."""
    units: list[ast.AST] = [tree]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            units.append(node)
    return units


def own_nodes(unit: ast.AST):
    """The unit's nodes, stopping at nested function boundaries —
    nested defs/lambdas are their own dataflow units and must never be
    evaluated under an enclosing function's name classification."""
    stack = list(ast.iter_child_nodes(unit))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(
            n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(n))


def base_name(node: ast.AST) -> str | None:
    """The root Name of a Name/Attribute/Subscript chain (``tgt[i]`` →
    ``tgt``; ``seq.prompt[-k:]`` → ``seq``), or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


# --------------------------------------------------------------- waivers
# One comment carries one or more `token(reason)` items; the reason is
# mandatory. Only real COMMENT tokens count — a docstring describing
# the syntax is not a waiver.
_WAIVER_COMMENT = re.compile(r"#\s*dynlint:\s*(.*)$")
_WAIVER_ITEM = re.compile(r"\s*,?\s*([a-z][a-z0-9-]*)(\(([^()]*)\))?")


def _iter_comments(source: str):
    """(lineno, col, text) for every comment token in the file."""
    import io
    import tokenize

    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # runner already reports unparseable files


def parse_waivers(
    rel_path: str, source: str, known_tokens: dict[str, str]
) -> tuple[dict[int, dict[str, str]], list[Finding]]:
    """Scan a file's ``# dynlint:`` comments.

    Returns ``({line: {rule: reason}}, waiver-syntax findings)`` where
    ``known_tokens`` maps waiver token → rule name. A token without a
    non-empty reason, or an unknown token, produces a ``waiver-syntax``
    finding and waives nothing.
    """
    waivers: dict[int, dict[str, str]] = {}
    findings: list[Finding] = []

    def bad(lineno: int, col: int, message: str) -> None:
        findings.append(
            Finding(
                rule="waiver-syntax",
                file=rel_path,
                line=lineno,
                col=col,
                message=message,
            )
        )

    for lineno, col, text in _iter_comments(source):
        m = _WAIVER_COMMENT.search(text)
        if not m:
            continue
        body = m.group(1).strip()
        if not body:
            bad(lineno, col, "empty dynlint comment")
            continue
        pos = 0
        while pos < len(body):
            item = _WAIVER_ITEM.match(body, pos)
            if item is None or item.end() == pos:
                bad(
                    lineno,
                    col,
                    f"malformed dynlint waiver near {body[pos:]!r}",
                )
                break
            pos = item.end()
            name, reason = item.group(1), (item.group(3) or "").strip()
            rule = known_tokens.get(name)
            if rule is None:
                bad(lineno, col, f"unknown dynlint waiver token {name!r}")
                continue
            if item.group(2) is None or not reason:
                bad(
                    lineno,
                    col,
                    f"waiver {name!r} requires a reason: "
                    f"# dynlint: {name}(<why this is safe>)",
                )
                continue
            waivers.setdefault(lineno, {})[rule] = reason
    return waivers, findings


def statement_spans(tree: ast.Module) -> list[tuple[int, int]]:
    """(lineno, end_lineno) of every statement — the waiver-coverage
    unit the docs promise ("any line of a multi-line statement").

    Compound statements (if/while/for/with/def/...) clamp to their
    HEADER lines only: a finding on an ``if`` test must not be waivable
    by a comment somewhere inside the block's body — the body's own
    statements are their own (smaller) spans."""
    spans: list[tuple[int, int]] = []
    for n in ast.walk(tree):
        if not isinstance(n, ast.stmt):
            continue
        lo, hi = n.lineno, n.end_lineno or n.lineno
        body = getattr(n, "body", None)
        if isinstance(body, list) and body and hasattr(body[0], "lineno"):
            hi = min(hi, max(lo, body[0].lineno - 1))
        spans.append((lo, hi))
    return spans


def apply_waivers(
    findings: list[Finding],
    waivers: dict[int, dict[str, str]],
    spans: list[tuple[int, int]] | None = None,
) -> set[tuple[int, str]]:
    """Mark findings waived in place: a waiver of the finding's rule
    anywhere on the smallest statement enclosing the flagged node
    covers it. Returns the consumed ``(line, rule)`` waiver entries so
    the runner can report stale waivers that match nothing."""
    consumed: set[tuple[int, str]] = set()
    for f in findings:
        lo, hi = f.line, f.end_line
        if spans:
            best = None
            for slo, shi in spans:
                if slo <= lo and hi <= shi:
                    if best is None or shi - slo < best[1] - best[0]:
                        best = (slo, shi)
            if best is not None:
                lo, hi = best
        for line in range(lo, hi + 1):
            reason = waivers.get(line, {}).get(f.rule)
            if reason is not None:
                f.waived = True
                f.reason = reason
                consumed.add((line, f.rule))
                break
    return consumed
