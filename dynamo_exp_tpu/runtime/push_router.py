"""PushRouter: policy-based dispatch over a Client's live instances.

Capability parity with
``/root/reference/lib/runtime/src/pipeline/network/egress/push_router.rs``:
random / round-robin / direct(instance) / static routing, presented as an
AsyncEngine so routers compose with pipelines. KV-aware routing lives in
:mod:`dynamo_exp_tpu.router` and plugs in via ``RouterMode.DIRECT``.

Fault tolerance (docs/fault_tolerance.md): selection skips draining and
breaker-blocked instances (the client's
:class:`~dynamo_exp_tpu.runtime.health.HealthTracker`); a
**connection/stream-start** failure — the transport refused, or the
stream died before its first frame — is retried with exponential backoff
+ jitter against a *different* instance, up to ``retries`` times and
never past the request's deadline.

**Resumable streams**: once the first frame has arrived, a break is no
longer a retry — naive re-issue would duplicate tokens — but it is no
longer fatal either. Journalable requests (engine-level dicts carrying
``token_ids``) get a :class:`~dynamo_exp_tpu.runtime.journal.ReplayJournal`:
every emitted token is recorded with its sequence index, and a mid-stream
break (worker crash, drain exceeding its grace period) re-dispatches a
**continuation request** — prompt + journaled tokens re-prefilled on a
different healthy instance, budget reduced by what was delivered, seed
pinned so the engine's counter-based sampler replays the exact draws —
up to ``max_recoveries`` times, never past the deadline, after which
:class:`RecoveryExhaustedError` surfaces (HTTP 502). In-band error frames
(``EngineError``) are application errors and are never retried or
resumed.
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import random
from typing import Any, AsyncIterator, Awaitable, Callable

from ..telemetry import get_telemetry, span as trace_span
from .client import Client
from .engine import (
    AsyncEngine,
    AsyncEngineContext,
    DeadlineExceededError,
    ResponseStream,
)
from .journal import ReplayJournal
from .transports.base import InstanceInfo


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round-robin"
    DIRECT = "direct"
    STATIC = "static"
    KV = "kv"


class NoInstancesError(ConnectionError):
    pass


class NoHealthyInstancesError(NoInstancesError):
    """Instances exist, but every one is draining, breaker-open, or
    already tried this request — the 503 + Retry-After case."""


class RecoveryExhaustedError(ConnectionError):
    """A resumable stream broke more than ``max_recoveries`` times (or
    past its deadline); the HTTP layer maps this to 502."""


class PushRouter(AsyncEngine[dict, Any]):
    """Routes each request to one live instance of a remote endpoint."""

    def __init__(
        self,
        client: Client,
        mode: RouterMode = RouterMode.RANDOM,
        ready_wait_s: float = 0.0,
        retries: int = 2,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        rng: random.Random | None = None,
        max_recoveries: int = 2,
        continuation_selector: (
            Callable[[list[int], frozenset[int]], Awaitable[int]] | None
        ) = None,
    ):
        self.client = client
        self.mode = mode
        # >0: a request arriving before any instance is discovered waits
        # this long for one instead of failing (ingress/graph startup
        # races); 0 keeps the strict fail-fast default.
        self.ready_wait_s = ready_wait_s
        self.retries = retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        # Injectable rng keeps backoff jitter (and journal seed pinning)
        # deterministic under test.
        self.rng = rng or random.Random()
        # Mid-stream failover budget per request; 0 disables journaling.
        self.max_recoveries = max_recoveries
        # KV-aware wrappers install a re-selector so a continuation still
        # lands on the best surviving prefix overlap (KvPushRouter).
        self.continuation_selector = continuation_selector
        self._rr = itertools.count()

    @property
    def health(self):
        return self.client.health

    def unavailable_ids(self) -> set[int]:
        """Live instance ids currently excluded from selection."""
        return self.health.unavailable_ids(self.client.instances)

    def _pick(
        self, request: dict, exclude: frozenset[int] | set[int] = frozenset()
    ) -> InstanceInfo:
        instances = self.client.instances
        if not instances:
            raise NoInstancesError("no live instances for endpoint")
        # An explicit target always wins, regardless of mode — KV-aware
        # callers (KvPushRouter) do their own health-filtered selection.
        if "_worker_instance_id" in request:
            try:
                return self.client.instance(int(request["_worker_instance_id"]))
            except KeyError as e:
                # Stale target (lease expired) is a routing error, so callers
                # can retry/503 with one except clause.
                raise NoInstancesError(str(e)) from e
        pool = [
            i
            for i in self.health.filter_available(instances)
            if i.instance_id not in exclude
        ]
        if not pool:
            raise NoHealthyInstancesError(
                f"no healthy instances for endpoint "
                f"({len(instances)} live, all draining/unhealthy/tried)"
            )
        if self.mode is RouterMode.RANDOM:
            return self.rng.choice(pool)
        if self.mode is RouterMode.ROUND_ROBIN:
            return pool[next(self._rr) % len(pool)]
        if self.mode in (RouterMode.DIRECT, RouterMode.KV):
            # The explicit-target branch above handles present ids.
            raise ValueError("direct routing requires _worker_instance_id")
        # STATIC: single fixed instance
        return pool[0]

    async def sleep_backoff(
        self, attempt: int, ctx: AsyncEngineContext
    ) -> None:
        """Exponential backoff with 50% jitter, capped by the deadline.
        Public: KV-aware wrappers reuse this policy for their own
        re-selecting retry loops."""
        delay = min(
            self.backoff_base_s * (2 ** (attempt - 1)), self.backoff_max_s
        )
        delay *= 0.5 + self.rng.random() / 2
        remaining = ctx.time_remaining()
        if remaining is not None:
            delay = min(delay, max(remaining, 0.0))
        if delay > 0:
            await asyncio.sleep(delay)

    async def _dispatch(
        self,
        request: dict,
        ctx: AsyncEngineContext,
        tried: set[int],
        pick: Callable[[], Awaitable[InstanceInfo] | InstanceInfo],
        retry_ok: bool,
    ):
        """One health-guarded dispatch loop: pick → acquire → open the
        stream, retrying stream-start failures against other instances.
        Every ``health.acquire`` is paired with exactly one of
        record_success / record_failure / release — a CancelledError (or
        any non-transport error) escaping between acquire and outcome
        must not strand the half-open probe slot (ROADMAP open item)."""
        attempt = 0
        while True:
            ctx.check_deadline("router")
            instance = pick()
            if asyncio.iscoroutine(instance):
                instance = await instance
            self.health.acquire(instance.instance_id)
            try:
                first, frames = await self.client.open_stream(
                    instance, request, ctx
                )
            except ConnectionError as e:
                # Stream-start failure: the instance never produced a
                # frame, so failing over cannot duplicate output.
                self.health.record_failure(instance.instance_id)
                tried.add(instance.instance_id)
                attempt += 1
                if not retry_ok or attempt > self.retries:
                    raise
                get_telemetry().request_retries.labels(
                    "connect" if _is_connect_error(e) else "stream_start"
                ).inc()
                await self.sleep_backoff(attempt, ctx)
                continue
            except BaseException:
                # No transport outcome (cancellation, bugs, deadline
                # races): free the probe slot without judging health.
                self.health.release(instance.instance_id)
                raise
            if (
                first is not None
                and first.is_error()
                and ctx.deadline_expired
            ):
                # The deadline expired in transit and the remote plane
                # refused in-band. That is neither an instance failure
                # nor an application error — surface it as the deadline
                # it is (HTTP maps this to 504, not 500). The probe slot
                # is released outcome-free: the expiry says nothing
                # about this instance's health.
                self.health.release(instance.instance_id)
                raise DeadlineExceededError(
                    first.error_message()
                    or f"request {ctx.id} deadline exceeded at request plane"
                )
            self.health.record_success(instance.instance_id)
            return instance, first, frames

    async def generate(
        self, request: dict, context: AsyncEngineContext | None = None
    ) -> ResponseStream[Any]:
        ctx = context or AsyncEngineContext()
        if not self.client.instances and self.ready_wait_s > 0:
            try:
                await self.client.wait_for_instances(1, self.ready_wait_s)
            except TimeoutError:
                pass  # fall through to the strict error below
        explicit_target = "_worker_instance_id" in request
        clean = {k: v for k, v in request.items() if k != "_worker_instance_id"}
        # Journal for mid-stream failover — engine-level requests only.
        # With an explicit target, recovery needs a re-selector (the
        # KV-aware wrapper's), otherwise the target is contractual.
        journal = None
        if self.max_recoveries > 0 and (
            not explicit_target or self.continuation_selector is not None
        ):
            journal = ReplayJournal.for_request(clean, self.rng)
        if journal is not None:
            clean = journal.request  # the seed-pinned copy
        tried: set[int] = set()
        instance, first, frames = await self._dispatch(
            clean,
            ctx,
            tried,
            pick=lambda: self._pick(request, exclude=tried),
            retry_ok=not explicit_target,
        )

        async def _emit(data) -> AsyncIterator[Any]:
            if journal is None:
                yield data
            else:
                out = journal.record(data)
                if out is not None:
                    yield out

        # Instances whose stream broke mid-flight for THIS request: a
        # continuation never lands on any of them again (cumulative
        # across recoveries, not just the most recent death).
        broken: set[int] = set()

        async def _data() -> AsyncIterator[Any]:
            nonlocal instance, first, frames
            while True:
                try:
                    if first is not None:
                        if first.is_error():
                            from .client import EngineError

                            raise EngineError(
                                first.error_message() or "remote error"
                            )
                        if first.data is not None:
                            async for out in _emit(first.data):
                                yield out
                        first = None
                    async for ann in frames:
                        if ann.data is not None:
                            async for out in _emit(ann.data):
                                yield out
                    return
                except ConnectionError as e:
                    if journal is None or journal.finished:
                        raise
                    done = journal.synthetic_finish()
                    if done is not None:
                        # The stream died between its last token and the
                        # finish frame; the budget is spent — close the
                        # stream locally instead of re-prefilling to
                        # generate nothing.
                        yield done
                        return
                    broken.add(instance.instance_id)
                    instance, first, frames = await self._recover(
                        journal, instance, e, ctx, broken
                    )

        return ResponseStream(_data(), ctx)

    async def _recover(
        self,
        journal: ReplayJournal,
        dead: InstanceInfo,
        err: ConnectionError,
        ctx: AsyncEngineContext,
        broken: set[int],
    ):
        """Mid-stream break: record the failure, then re-dispatch the
        journal's continuation request to a different healthy instance —
        never one whose stream already broke for this request
        (``broken`` accumulates across recoveries). Bounded by
        ``max_recoveries`` and the request's deadline."""
        self.health.record_failure(dead.instance_id)
        if journal.recoveries >= self.max_recoveries:
            raise RecoveryExhaustedError(
                f"stream for request {ctx.id} broke "
                f"{journal.recoveries + 1} times "
                f"(max_recoveries={self.max_recoveries}): {err}"
            ) from err
        if ctx.deadline_expired:
            # No recovery after the deadline: the client has given up.
            raise DeadlineExceededError(
                f"request {ctx.id} deadline exceeded during mid-stream "
                f"recovery (stream broke: {err})"
            ) from err
        journal.recoveries += 1
        # Cause attribution: a spot-reclaimed worker's break counts as
        # "reclaim" (it also says "drain"-adjacent things, so test
        # reclaim first); a drain-grace expiry as "drain"; anything else
        # as a plain stream drop.
        msg = str(err).lower()
        if "reclaim" in msg:
            reason = "reclaim"
        elif "drain" in msg:
            reason = "drain"
        else:
            reason = "stream_drop"
        get_telemetry().request_recoveries.labels(reason).inc()
        cont = journal.continuation_request()
        tried = set(broken)
        # The recovery span marks the re-prefill hop in the request's
        # trace timeline (`llmctl trace <id>`).
        with trace_span(
            "recovery",
            request_id=ctx.id,
            reason=reason,
            recovery=journal.recoveries,
            journaled_tokens=len(journal.tokens),
            dead_instance=dead.instance_id,
        ) as sp:
            instance, first, frames = await self._dispatch(
                cont,
                ctx,
                tried,
                pick=lambda: self._pick_continuation(cont, tried),
                retry_ok=True,
            )
            sp.set(instance_id=instance.instance_id)
        journal.begin_continuation()
        return instance, first, frames

    def _pick_continuation(self, cont: dict, tried: set[int]):
        """Continuation placement: the KV-aware re-selector when
        installed (it sees prompt+journal, so the overlap estimate
        includes the re-prefill), plain health-filtered policy pick
        otherwise. Never the instance(s) that already failed this
        request."""
        if self.continuation_selector is None:
            return self._pick(cont, exclude=tried)

        async def _select() -> InstanceInfo:
            wid = await self.continuation_selector(
                cont.get("token_ids", []), frozenset(tried)
            )
            try:
                return self.client.instance(int(wid))
            except KeyError as e:
                raise NoInstancesError(str(e)) from e

        return _select()

    async def generate_direct(
        self,
        request: dict,
        instance_id: int,
        context: AsyncEngineContext | None = None,
    ) -> ResponseStream[Any]:
        return await self.generate(
            {**request, "_worker_instance_id": instance_id}, context
        )


def _is_connect_error(e: Exception) -> bool:
    """Connect-phase errors mention the transport; stream drops happen
    after dispatch. Best-effort label for the retry counter."""
    return "connect" in str(e).lower() or "no served endpoint" in str(e).lower()
