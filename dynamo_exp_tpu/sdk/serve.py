"""Graph supervisor: one process per service worker, TPU chips allocated.

Reference parity: ``deploy/dynamo/sdk/cli/serving.py:58-187`` (circus
arbiter with one watcher per service, GPU allocation, per-watcher env) —
rebuilt on plain subprocesses with restart-with-backoff.

    python -m dynamo_exp_tpu.sdk.serve pkg.module:RootClass \
        [-f config.yaml] [--coordinator HOST:PORT | --start-coordinator] \
        [--service-name OnlyThisOne] [--tpu-chips N]
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import logging
import os
import signal
import sys
import time

logger = logging.getLogger("dynamo_exp_tpu.sdk.serve")

MAX_RESTARTS = 3
RESTART_WINDOW_S = 60.0


class Watcher:
    """One service worker process, restarted on unexpected death."""

    def __init__(self, spec, worker_idx: int, argv: list[str], env: dict[str, str]):
        self.spec = spec
        self.worker_idx = worker_idx
        self.argv = argv
        self.env = env
        self.proc: asyncio.subprocess.Process | None = None
        self.restarts: list[float] = []
        self.stopping = False

    @property
    def name(self) -> str:
        return f"{self.spec.name}[{self.worker_idx}]"

    async def start(self) -> None:
        self.proc = await asyncio.create_subprocess_exec(
            *self.argv, env={**os.environ, **self.env}
        )
        logger.info("started %s (pid %d)", self.name, self.proc.pid)

    async def supervise(self) -> None:
        while not self.stopping:
            rc = await self.proc.wait()
            if self.stopping:
                return
            now = time.monotonic()
            self.restarts = [
                t for t in self.restarts if now - t < RESTART_WINDOW_S
            ] + [now]
            if len(self.restarts) > MAX_RESTARTS:
                raise RuntimeError(
                    f"{self.name} crashed {len(self.restarts)} times in "
                    f"{RESTART_WINDOW_S:.0f}s (rc={rc}); giving up"
                )
            logger.warning("%s exited rc=%s; restarting", self.name, rc)
            await asyncio.sleep(min(2 ** (len(self.restarts) - 1), 10))
            await self.start()

    async def stop(self, timeout: float = 20.0) -> None:
        self.stopping = True
        if self.proc is None or self.proc.returncode is not None:
            return
        self.proc.terminate()  # SIGTERM -> graceful drain in the child
        with contextlib.suppress(asyncio.TimeoutError):
            await asyncio.wait_for(self.proc.wait(), timeout)
        if self.proc.returncode is None:
            self.proc.kill()
            await self.proc.wait()


async def serve_graph(args) -> None:
    from ..runtime.transports.coordinator import CoordinatorServer
    from .allocator import TPUAllocator
    from .config import ENV_VAR, ServiceConfig
    from .serve_service import load_target
    from .service import discover_graph

    root = load_target(args.target)
    graph = discover_graph(root)
    if args.service_name:
        graph = [s for s in graph if s.name == args.service_name]
        if not graph:
            raise SystemExit(f"no service named {args.service_name!r}")

    coordinator = None
    endpoint = args.coordinator
    if args.start_coordinator:
        coordinator = CoordinatorServer("127.0.0.1", args.coordinator_port)
        await coordinator.start()
        endpoint = coordinator.address
        print(f"coordinator on {endpoint}", flush=True)
    if not endpoint:
        raise SystemExit("need --coordinator or --start-coordinator")

    config = ServiceConfig.load(args.config)
    allocator = TPUAllocator(args.tpu_chips)
    watchers: list[Watcher] = []
    for spec in graph:
        for w in range(spec.workers):
            env = {
                "DYN_RUNTIME_COORDINATOR_ENDPOINT": endpoint,
                ENV_VAR: config.dumps(),
                **allocator.assign(spec.name, int(spec.resources.get("tpu", 0))),
            }
            argv = [
                sys.executable,
                "-m",
                "dynamo_exp_tpu.sdk.serve_service",
                args.target,
                "--service-name",
                spec.name,
            ]
            watchers.append(Watcher(spec, w, argv, env))

    for w in watchers:
        await w.start()
    print(f"serving {len(watchers)} workers: "
          f"{[w.name for w in watchers]}", flush=True)
    tasks = [asyncio.ensure_future(w.supervise()) for w in watchers]
    try:
        done, _ = await asyncio.wait(tasks, return_when=asyncio.FIRST_EXCEPTION)
        for t in done:
            t.result()  # propagate give-up errors
    finally:
        for t in tasks:
            t.cancel()
        await asyncio.gather(
            *[w.stop() for w in watchers], return_exceptions=True
        )
        if coordinator is not None:
            await coordinator.close()


def main(argv: list[str] | None = None) -> None:
    logging.basicConfig(level="INFO")
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("target", help="pkg.module:RootClass")
    p.add_argument("-f", "--config", default=None, help="service config YAML")
    p.add_argument("--coordinator", default=os.environ.get("DYN_COORDINATOR", ""))
    p.add_argument("--start-coordinator", action="store_true")
    p.add_argument("--coordinator-port", type=int, default=0)
    p.add_argument("--service-name", default=None, help="run one service only")
    p.add_argument("--tpu-chips", type=int, default=None,
                   help="host chip budget (default: env DYN_TPU_CHIPS or 4)")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])

    loop = asyncio.new_event_loop()
    task = loop.create_task(serve_graph(args))
    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError, ValueError):
            loop.add_signal_handler(sig, task.cancel)
    try:
        loop.run_until_complete(task)
    except asyncio.CancelledError:
        pass
    finally:
        loop.close()


if __name__ == "__main__":
    main()
