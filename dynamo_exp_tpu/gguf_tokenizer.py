"""Tokenizer reconstruction from GGUF-embedded metadata.

Capability parity with the reference's GGUF tokenizer conversion
(``/root/reference/lib/llm/src/gguf/gguf_tokenizer.rs:1-260``, itself
following transformers' convert_slow_tokenizer recipe): a bare ``.gguf``
carries its full tokenizer under ``tokenizer.ggml.*`` — token strings,
unigram scores or BPE merges, token types, special-token ids — and must
serve end-to-end WITHOUT a side tokenizer.json.

Two embedded models are supported, same as the reference:

- ``llama``  → SentencePiece-style **Unigram**: vocab = (token, score)
  pairs, byte fallback, the ``▁``-prefix normalizer and the matching
  decoder chain.
- ``gpt2``   → byte-level **BPE**: vocab + space-separated merge pairs,
  ByteLevel pre-tokenizer/decoder.

Everything is built with the HF ``tokenizers`` Python API — the same
library the rest of the stack already uses — so DecodeStream and the
preprocessor work identically whether the tokenizer came from
tokenizer.json, tokenizer.model (see ``sp_model.py``), or a GGUF.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

# tokenizer.ggml.token_type values (llama.cpp enum).
TOKEN_NORMAL = 1
TOKEN_UNKNOWN = 2
TOKEN_CONTROL = 3
TOKEN_USER_DEFINED = 4
TOKEN_UNUSED = 5
TOKEN_BYTE = 6


def _build_unigram(tokens, scores, unk_id: int | None):
    """SentencePiece-as-Unigram with the canonical normalizer/decoder
    chain (reference: gguf_tokenizer.rs unigram_tokenizer)."""
    from tokenizers import Tokenizer, decoders, models, normalizers

    if scores is None:
        raise ValueError(
            "llama-model GGUF tokenizer is missing tokenizer.ggml.scores"
        )
    vocab = [(t, float(s)) for t, s in zip(tokens, scores)]
    tok = Tokenizer(
        models.Unigram(vocab, unk_id=unk_id if unk_id is not None else 0,
                       byte_fallback=True)
    )
    tok.normalizer = normalizers.Sequence(
        [normalizers.Prepend("▁"), normalizers.Replace(" ", "▁")]
    )
    tok.decoder = decoders.Sequence(
        [
            decoders.Replace("▁", " "),
            decoders.ByteFallback(),
            decoders.Fuse(),
            decoders.Strip(" ", 1, 0),
        ]
    )
    return tok


def _build_bpe(tokens, merges):
    """Byte-level BPE (reference: gguf_tokenizer.rs bpe_tokenizer)."""
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers

    if merges is None:
        raise ValueError(
            "gpt2-model GGUF tokenizer is missing tokenizer.ggml.merges"
        )
    vocab = {t: i for i, t in enumerate(tokens)}
    merge_pairs = []
    for m in merges:
        a, _, b = m.partition(" ")
        merge_pairs.append((a, b))
    tok = Tokenizer(models.BPE(vocab=vocab, merges=merge_pairs))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    return tok


def tokenizer_backend_from_gguf(gguf):
    """Build a ``tokenizers.Tokenizer`` from a parsed ``GGUFFile`` (or
    any object with a ``metadata`` dict)."""
    md = gguf.metadata
    model = md.get("tokenizer.ggml.model")
    tokens = md.get("tokenizer.ggml.tokens")
    if model is None or tokens is None:
        raise ValueError(
            "GGUF has no embedded tokenizer "
            "(tokenizer.ggml.model/tokens missing)"
        )
    token_type = md.get("tokenizer.ggml.token_type")
    unk_id = md.get("tokenizer.ggml.unknown_token_id")
    if unk_id is None and token_type is not None:
        unk = [i for i, t in enumerate(token_type) if t == TOKEN_UNKNOWN]
        unk_id = unk[0] if unk else None

    if model in ("llama", "replit"):
        tok = _build_unigram(tokens, md.get("tokenizer.ggml.scores"), unk_id)
    elif model == "gpt2":
        tok = _build_bpe(tokens, md.get("tokenizer.ggml.merges"))
    else:
        raise ValueError(f"unsupported GGUF tokenizer model {model!r}")

    # Special tokens: bos/eos/unk plus every CONTROL-typed token, marked
    # special so skip_special_tokens decoding drops them.
    from tokenizers import AddedToken

    special_ids = {
        md.get("tokenizer.ggml.bos_token_id"),
        md.get("tokenizer.ggml.eos_token_id"),
        unk_id,
    } - {None}
    if token_type is not None:
        special_ids.update(
            i for i, t in enumerate(token_type) if t == TOKEN_CONTROL
        )
    specials = [
        AddedToken(tokens[i], special=True)
        for i in sorted(special_ids)
        if i < len(tokens)
    ]
    if specials:
        tok.add_special_tokens(specials)

    # add_bos_token: prepend BOS via a template post-processor, the same
    # behavior HF llama tokenizers encode in tokenizer.json. When the
    # key is absent, llama.cpp defaults SPM (unigram) vocabularies to
    # add_bos=true and BPE to false — older GGUF exports rely on that.
    bos_id = md.get("tokenizer.ggml.bos_token_id")
    if bos_id is not None and bos_id >= len(tokens):
        raise ValueError(
            f"GGUF bos_token_id {bos_id} out of range for vocab of {len(tokens)}"
        )
    default_add_bos = model in ("llama", "replit")
    if md.get("tokenizer.ggml.add_bos_token", default_add_bos) and bos_id is not None:
        from tokenizers import processors

        bos = tokens[bos_id]
        tok.post_processor = processors.TemplateProcessing(
            single=f"{bos} $A",
            pair=f"{bos} $A {bos} $B",
            special_tokens=[(bos, bos_id)],
        )
    return tok


def tokenizer_from_gguf(path: str):
    """Load a serving ``Tokenizer`` facade straight from a ``.gguf``."""
    from .models.gguf import GGUFFile
    from .tokenizer import Tokenizer

    gguf = GGUFFile.parse(path)
    backend = tokenizer_backend_from_gguf(gguf)
    eos = gguf.metadata.get("tokenizer.ggml.eos_token_id")
    return Tokenizer(backend, [int(eos)] if eos is not None else [])
