"""Layered runtime configuration: defaults <- config file <- environment.

Reference capability: figment layering in
``/root/reference/lib/runtime/src/config.rs:26-146`` with ``DYN_RUNTIME_*``
env prefixes. We keep the same shape: a dataclass of defaults, optionally
overridden by a YAML/JSON file, then by ``DYN_*`` environment variables.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Any

TRUTHY = {"1", "true", "yes", "on"}


def env_is_truthy(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in TRUTHY


@dataclass
class RuntimeConfig:
    """Process-level runtime settings."""

    num_blocking_threads: int = 8
    # Control-plane (coordinator) and request-plane (broker) addresses.
    # Empty => "static" mode: no discovery, endpoints wired explicitly.
    coordinator_endpoint: str = ""
    broker_endpoint: str = ""
    # TCP response-plane bind host (the address handed to peers).
    response_host: str = "127.0.0.1"
    response_port: int = 0  # 0 = ephemeral
    # Lease TTL for discovery registrations, seconds.
    lease_ttl_s: float = 10.0
    log_jsonl: bool = False
    log_level: str = "INFO"

    ENV_PREFIX = "DYN_RUNTIME_"

    @classmethod
    def from_settings(cls, config_path: str | None = None) -> "RuntimeConfig":
        values: dict[str, Any] = {}
        path = config_path or os.environ.get("DYN_RUNTIME_CONFIG")
        if path and Path(path).exists():
            text = Path(path).read_text()
            if path.endswith((".yaml", ".yml")):
                import yaml

                values.update(yaml.safe_load(text) or {})
            else:
                values.update(json.loads(text))
        for f in dataclasses.fields(cls):
            if f.name == "ENV_PREFIX":
                continue
            env_name = cls.ENV_PREFIX + f.name.upper()
            if env_name in os.environ:
                raw = os.environ[env_name]
                if f.type in ("int", int):
                    values[f.name] = int(raw)
                elif f.type in ("float", float):
                    values[f.name] = float(raw)
                elif f.type in ("bool", bool):
                    values[f.name] = raw.strip().lower() in TRUTHY
                else:
                    values[f.name] = raw
        known = {f.name for f in dataclasses.fields(cls)}
        values = {k: v for k, v in values.items() if k in known}
        return cls(**values)

    @property
    def is_static(self) -> bool:
        return not self.coordinator_endpoint
