"""Global prefix index: which workers hold which KV blocks.

Capability parity with ``/root/reference/lib/llm/src/kv_router/indexer.rs``
(``RadixTree::{find_matches,apply_event,remove_worker}`` :239-391,
``KvIndexer`` :499-608, ``KvIndexerSharded`` :677-790), built on the
SAME radix structure the owning engines match against
(:class:`dynamo_exp_tpu.kv.PrefixIndex`): one tree per worker, fed by
the stored/removed event stream. An overlap query walks each worker's
tree exactly like that worker's own page manager would walk its index —
the score IS the per-instance coverage, not an approximation — and the
tree's orphan semantics mean a mid-chain eviction detaches (not
destroys) the suffix, restoring full coverage if the block is
re-registered.

Single-writer: events are applied on the indexer's asyncio task, queries
run on the same loop — the same discipline the reference enforces with
its event channel.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import Sequence

from ..kv import PrefixIndex
from ..tokens import HASH_ALGO_VERSION, compute_block_hashes_for_seq
from .protocols import KvCacheEventData, OverlapScores, RouterEvent

logger = logging.getLogger(__name__)


class RadixIndex:
    """Per-worker radix prefix trees over chained block hashes."""

    def __init__(self):
        self._per_worker: dict[int, PrefixIndex] = {}

    def apply_event(self, event: RouterEvent) -> None:
        if event.hash_version != HASH_ALGO_VERSION:
            # Warned once at decode (protocols.from_dict). A mismatched
            # peer's hashes live in a disjoint seed space and can never
            # match a local query — indexing them would only grow
            # unmatchable state for the life of that worker.
            return
        w = event.worker_id
        data: KvCacheEventData = event.data
        if data.kind == "stored":
            index = self._per_worker.setdefault(w, PrefixIndex())
            # Within one event the hashes chain: parent_hash parents the
            # first block, each block parents the next (the engine emits
            # one block per event; batched senders chain).
            parent = data.parent_hash
            for h in data.block_hashes:
                index.insert(parent, h)
                parent = h
        elif data.kind == "removed":
            index = self._per_worker.get(w)
            if index is None:
                return
            for h in data.block_hashes:
                index.remove(h)
            if not index.num_blocks:
                del self._per_worker[w]
        else:
            logger.warning("unknown kv event kind %r", data.kind)

    def remove_worker(self, worker_id: int) -> None:
        self._per_worker.pop(worker_id, None)

    def find_matches(self, seq_hashes: Sequence[int]) -> OverlapScores:
        """Longest contiguous matched prefix per worker — each worker's
        tree is walked root-anchored, the same match its engine-side
        page manager performs at admission."""
        scores: dict[int, int] = {}
        for w, index in self._per_worker.items():
            n = index.coverage_blocks(seq_hashes)
            if n > 0:
                scores[w] = n
        return OverlapScores(scores)

    @property
    def num_blocks(self) -> int:
        """Distinct (worker, block) registrations still indexed."""
        return sum(ix.num_blocks for ix in self._per_worker.values())


class KvIndexer:
    """Event-pump wrapper: subscribes to a subject on the event plane and
    keeps the index current; offers block hashing + match queries."""

    def __init__(self, block_size: int):
        self.block_size = block_size
        self.index = RadixIndex()
        self._task: asyncio.Task | None = None
        self.events_applied = 0

    def block_hashes(self, token_ids: Sequence[int]) -> list[int]:
        return compute_block_hashes_for_seq(token_ids, self.block_size)

    def find_matches_for_request(self, token_ids: Sequence[int]) -> OverlapScores:
        return self.index.find_matches(self.block_hashes(token_ids))

    def apply(self, event: RouterEvent) -> None:
        self.index.apply_event(event)
        self.events_applied += 1

    def remove_worker(self, worker_id: int) -> None:
        self.index.remove_worker(worker_id)

    async def start(self, event_plane, subject: str) -> None:
        if self._task is not None:
            return

        # Subscribe (fully registered on return) before the task runs so no
        # event can slip between start() returning and the pump's first
        # iteration.
        subscription = await event_plane.subscribe(subject)

        async def pump():
            async for payload in subscription:
                try:
                    self.apply(RouterEvent.from_dict(payload))
                except Exception:
                    logger.exception("bad kv event: %r", payload)

        self._task = asyncio.create_task(pump(), name=f"kv-indexer[{subject}]")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None


class KvIndexerSharded:
    """Shards the index by hash for very large clusters (reference:
    ``KvIndexerSharded``, indexer.rs:677-790). Queries fan out and merge."""

    def __init__(self, block_size: int, num_shards: int = 4):
        self.block_size = block_size
        self.shards = [RadixIndex() for _ in range(num_shards)]

    def _shard(self, worker_id: int) -> RadixIndex:
        return self.shards[worker_id % len(self.shards)]

    def apply(self, event: RouterEvent) -> None:
        self._shard(event.worker_id).apply_event(event)

    def remove_worker(self, worker_id: int) -> None:
        self._shard(worker_id).remove_worker(worker_id)

    def find_matches_for_request(self, token_ids: Sequence[int]) -> OverlapScores:
        hashes = compute_block_hashes_for_seq(token_ids, self.block_size)
        merged: dict[int, int] = {}
        for shard in self.shards:
            merged.update(shard.find_matches(hashes).scores)
        return OverlapScores(merged)
