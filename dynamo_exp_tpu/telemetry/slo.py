"""Shared SLO / goodput attribution (docs/observability.md "SLO
attribution & goodput").

One request either met its latency targets or it didn't — and the
answer must be computed by exactly one piece of code, wherever the
question is asked:

- the **live HTTP edge** measures per-request TTFT/ITL as the stream
  drains and feeds them here (prometheus counters
  ``dynamo_slo_violations_total{slo,priority}`` /
  ``dynamo_goodput_requests_total{priority}``);
- the **live planner** reads its ``plan_step_slo`` p99 pressure inputs
  from this window (``window_percentiles``), not from a separate
  histogram pipeline;
- the **cluster simulator** counts ``SimReport`` goodput/violations and
  derives its planner pressure through the very same class — so a
  policy tuned in simulation is judged by the counter the live fleet
  will export (the calibration loop docs/simulation.md describes).

``percentile`` lives here (nearest-rank, p99-of-2-samples-is-the-max)
and is re-exported by ``sim/report.py`` — one percentile definition for
the report, the pressure inputs, and the dispatch-profiler summaries.

PR 16 grows two drift-watch surfaces on top (docs/observability.md
"SLO burn rate & workload drift"): **multi-window burn rate** — each
SLO axis keeps a fast (last 64 requests) and slow (last 1024) breach
window, exported as ``dynamo_slo_burn_rate{slo,window}``, the SRE-style
"fast window pages, slow window confirms" pair — and the module hosts
the glue between :mod:`telemetry.fingerprint` and the engine's
``dynamo_workload_drift_score`` gauge.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from dataclasses import dataclass

# Admission priority classes (http/admission.py) -> counter label names.
PRIORITY_NAMES = {0: "low", 1: "normal", 2: "high"}

# Burn-rate window sizes, in completed requests. Request-count windows
# (not wall-clock) keep the math deterministic and meaningful at any
# throughput: 64 requests of signal at 1 rps or 1000 rps is the same
# statistical confidence.
BURN_WINDOWS = (("fast", 64), ("slow", 1024))


def percentile(samples: list[float], q: float) -> float | None:
    """Nearest-rank percentile: ``sorted[ceil(q*n) - 1]``. On a 2-sample
    window p99 is the MAX, not the min — these window percentiles feed
    the SLO planner's pressure terms, and flooring the rank would hide
    a breached tail exactly in low-throughput windows. None on no
    samples."""
    if not samples:
        return None
    s = sorted(samples)
    rank = min(max(math.ceil(q * len(s)), 1), len(s))
    return s[rank - 1]


@dataclass(frozen=True)
class SloConfig:
    """Per-request latency targets. ``None`` means the axis is not an
    SLO (it is still measured for the pressure window, never counted as
    a violation)."""

    ttft_s: float | None = None
    itl_s: float | None = None

    @property
    def active(self) -> bool:
        return self.ttft_s is not None or self.itl_s is not None


class SloAttribution:
    """Windowed TTFT/ITL attribution against :class:`SloConfig` targets.

    Thread-safe (the HTTP edge records from request tasks while the
    planner reads the window). Two surfaces:

    - ``observe_ttft`` / ``observe_itl`` feed the *pressure window*
      (``window_percentiles`` → ``reset_window`` per adjustment
      interval);
    - ``count`` attributes one *completed* request: each breached
      target increments its violation counter, a request breaching
      nothing counts as goodput. Shed/errored requests are never fed
      here — they have their own counters and contribute no goodput by
      construction.

    ``record`` composes both for call sites (the live edge) that learn
    TTFT and ITL at the same moment; the simulator calls the pieces at
    the instants its event loop learns them.
    """

    def __init__(
        self,
        cfg: SloConfig | None = None,
        telemetry=None,
        window: int = 4096,
    ):
        self.cfg = cfg or SloConfig()
        self._tel = telemetry
        self._lock = threading.Lock()
        self._window = window
        self.completed = 0
        self.violations: dict[str, int] = {"ttft": 0, "itl": 0}
        self.goodput_by_priority: dict[str, int] = {}
        # Bounded: a deployment with SLO flags but no planner pulling
        # (and resetting) the window must not leak two floats per
        # request forever — the window self-truncates to the most
        # recent ``window`` samples, which is also the right percentile
        # basis when nobody resets it.
        self._win_ttft: deque[float] = deque(maxlen=window)
        self._win_itl: deque[float] = deque(maxlen=window)
        # Burn-rate windows: per (slo axis, window name), a bounded
        # deque of 0/1 breach outcomes for requests where that axis was
        # measurable. Fed under the same lock as the attribution
        # counters (one more guarded field in the zones.py manifest).
        self._burn: dict[tuple[str, str], deque[int]] = {
            (slo, wname): deque(maxlen=size)
            for slo in ("ttft", "itl")
            for wname, size in BURN_WINDOWS
        }

    # ------------------------------------------------------ pressure window
    def observe_ttft(self, ttft_s: float) -> None:
        with self._lock:
            self._win_ttft.append(ttft_s)

    def observe_itl(self, itl_s: float) -> None:
        with self._lock:
            self._win_itl.append(itl_s)

    def window_percentiles(self) -> tuple[float | None, float | None]:
        """(p99 TTFT, p99 ITL) over the current window — the exact
        ``PlannerObservation.ttft_p99_s`` / ``itl_p99_s`` pressure
        inputs ``plan_step_slo`` consumes, live and simulated."""
        with self._lock:
            return (
                percentile(list(self._win_ttft), 0.99),
                percentile(list(self._win_itl), 0.99),
            )

    def reset_window(self) -> None:
        """Clear the pressure window (one call per adjustment interval;
        mirrors the live planner's stale-sample discipline)."""
        with self._lock:
            self._win_ttft = deque(maxlen=self._window)
            self._win_itl = deque(maxlen=self._window)

    # -------------------------------------------------------- attribution
    @staticmethod
    def priority_name(priority) -> str:
        if isinstance(priority, str):
            return priority
        return PRIORITY_NAMES.get(priority, str(priority))

    def count(
        self,
        priority,
        ttft_s: float | None = None,
        itl_s: float | None = None,
    ) -> tuple[str, ...]:
        """Attribute one completed request; returns the breached SLOs
        (``()`` = goodput). A target left ``None`` in the config — or a
        latency the caller couldn't measure (e.g. ITL of a 1-token
        response) — never counts as a violation."""
        violated = []
        if (
            self.cfg.ttft_s is not None
            and ttft_s is not None
            and ttft_s > self.cfg.ttft_s
        ):
            violated.append("ttft")
        if (
            self.cfg.itl_s is not None
            and itl_s is not None
            and itl_s > self.cfg.itl_s
        ):
            violated.append("itl")
        name = self.priority_name(priority)
        rates: list[tuple[str, str, float]] = []
        with self._lock:
            self.completed += 1
            for v in violated:
                self.violations[v] += 1
            if not violated:
                self.goodput_by_priority[name] = (
                    self.goodput_by_priority.get(name, 0) + 1
                )
            # Feed every axis that was *measurable* on this request —
            # a met target is a 0, so the window denominator is real
            # traffic, not just breaches.
            for slo, measured in (("ttft", ttft_s), ("itl", itl_s)):
                target = getattr(self.cfg, f"{slo}_s")
                if target is None or measured is None:
                    continue
                for wname, _size in BURN_WINDOWS:
                    win = self._burn[(slo, wname)]
                    win.append(1 if slo in violated else 0)
                    rates.append((slo, wname, sum(win) / len(win)))
        if self._tel is not None:
            for v in violated:
                self._tel.slo_violations.labels(v, name).inc()
            if not violated:
                self._tel.goodput_requests.labels(name).inc()
            for slo, wname, rate in rates:
                self._tel.slo_burn_rate.labels(slo, wname).set(rate)
        return tuple(violated)

    def record(
        self,
        priority,
        ttft_s: float | None = None,
        itl_s: float | None = None,
    ) -> tuple[str, ...]:
        """Observe into the pressure window AND attribute, in one call
        (the live edge learns both at stream end)."""
        if ttft_s is not None:
            self.observe_ttft(ttft_s)
        if itl_s is not None:
            self.observe_itl(itl_s)
        return self.count(priority, ttft_s=ttft_s, itl_s=itl_s)

    # ------------------------------------------------------------- totals
    @property
    def goodput_total(self) -> int:
        with self._lock:
            return sum(self.goodput_by_priority.values())

    def burn_rates(self) -> dict[str, float]:
        """Current breach fraction per ``"<slo>/<window>"`` key, e.g.
        ``{"ttft/fast": 0.05, "ttft/slow": 0.01, ...}``. Only windows
        that have received at least one measurable request appear —
        the ``metrics()["slo_burn_rate"]`` mirror shape."""
        with self._lock:
            return {
                f"{slo}/{wname}": round(sum(win) / len(win), 4)
                for (slo, wname), win in self._burn.items()
                if win
            }
