"""Multimodal seam tests: soft-token forward + the encode worker graph.

Reference capability anchor: ``examples/multimodal/components/
encode_worker.py:21-60`` (separate encode worker streaming image
features into the LLM's input sequence).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_exp_tpu.models import TINY, forward, init_kv_cache, init_params


def test_forward_token_embeds_matches_id_lookup():
    """Soft tokens that equal the embedding rows must reproduce the
    id-based forward exactly — pins the token_embeds seam."""
    cfg = dataclasses.replace(TINY, dtype="float32")
    params = init_params(jax.random.PRNGKey(1), cfg)
    toks = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
    pos = jnp.arange(8, dtype=jnp.int32)[None]
    table = jnp.asarray([[1]], jnp.int32)

    def run(**kw):
        k, v = init_kv_cache(cfg, num_pages=4, page_size=8, dtype=jnp.float32)
        out, _, _ = forward(params, cfg, toks, pos, table, k, v, **kw)
        return np.asarray(out)

    embeds = jnp.take(params["embed"], toks, axis=0)
    np.testing.assert_allclose(
        run(token_embeds=embeds), run(), atol=1e-6
    )


def test_patch_encoder_shapes():
    from examples.multimodal.components.encode_worker import PatchEncoder

    enc = PatchEncoder(hidden_size=64, patch=8)
    img = np.random.RandomState(0).rand(32, 24, 3)
    out = enc(img)
    assert out.shape == (4 * 3, 64)  # 32/8 x 24/8 patches


async def test_encode_worker_to_vision_chat_flow():
    """The demo graph end-to-end in-process: encode → soft-token prefill
    → a sampled token."""
    from examples.multimodal.components.encode_worker import EncodeWorker
    from examples.multimodal.multimodal_demo import VisionChat

    enc = EncodeWorker()
    enc.hidden_size = 64
    enc.patch = 8
    await enc.build()

    chat = VisionChat()
    await chat.build()

    # Wire the dependency by hand (no supervisor in this test).
    class _Dep:
        async def generate(self, request):
            async def gen():
                async for item in enc.encode(request):
                    yield item

            return gen()

    VisionChat.encoder._client = _Dep()
    img = np.random.RandomState(1).rand(16, 16, 3)
    results = []
    async for item in chat.generate(
        {"pixels": img.tolist(), "token_ids": [5, 7, 9]}
    ):
        results.append(item)
    VisionChat.encoder._client = None
    assert results
    assert results[0]["n_image_tokens"] == 4  # 16/8 x 16/8
    assert 0 <= results[0]["next_token"] < TINY.vocab_size
